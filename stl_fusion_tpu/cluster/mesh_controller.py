"""MeshController — elastic multi-host mesh membership (ISSUE 16).

PR 15 proved the honest 2-host mesh and measured its production weakness:
the stock ``jax.distributed`` world is all-or-nothing. Any task death
propagates a fatal coordination-service error that ABORTS every survivor
(measured rc=-6 inside ``PollForError``, no Python frame on the stack),
so a host kill forced a full survivor restart — 71.8 s in MULTICHIP_r07.
This module is the replacement failure-domain owner:

- **Evidence convergence.** A peer is declared dead only when independent
  signals agree: heartbeat lapse on the shared board, a
  :class:`~stl_fusion_tpu.resilience.PeerCircuitBreaker` stuck open, the
  orchestrator's ``peer-dead`` flag, or a round-deadline overrun (the
  wedged-collective tell). Each signal carries a weight; death needs the
  sum to reach ``evidence_threshold`` — a heartbeat lapse alone (e.g. a
  DCN partition window) never kills a member, which is exactly what the
  ``mesh_partition`` chaos scenario certifies.
- **Counted degrade, never silent, never downtime.** On convergence the
  controller records ``mesh_degraded`` in the ResilienceEvents ledger,
  abandons the wedged world in-process
  (:func:`~.multihost.teardown_world` — the survivor process NEVER
  restarts; the blocked dispatch thread is a documented zombie), and the
  caller keeps serving its local shards eager/single-host while the
  re-form runs.
- **Re-form ladder.** Survivors re-elect a coordinator through the shared
  rendezvous board: the lowest-ranked survivor publishes a *call* (new
  epoch, member order, fresh coordinator port) with O_EXCL atomicity;
  every other survivor polls for it, and takes over publishing after a
  rank-staggered timeout if the caller-elect is itself dead. World
  formation retries on a jittered, capped, exponential backoff — every
  attempt counted (``mesh_reform_attempt`` / ``mesh_reform_failed`` /
  ``mesh_reform_ok``), no retry invisible.
- **Live JOIN.** A joiner writes a board request and polls for the first
  call that names it; members absorb pending joiners at the next round
  boundary by re-forming to N+1 (``mesh_join_absorbed``) and rebalancing
  shards onto the joiner via the ShardMap/warm-restore machinery the
  caller owns.

The controller is deliberately jax-free: world mechanics arrive through a
``WorldOps`` adapter (:class:`JaxWorldOps` in production, fakes in unit
tests), and time/randomness are injected so every ladder transition is
deterministic under test.
"""
from __future__ import annotations

import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..resilience.events import ResilienceEvents, global_events

__all__ = [
    "EVIDENCE_WEIGHTS",
    "JaxWorldOps",
    "MeshController",
    "MeshReformError",
    "PeerEvidence",
    "RendezvousBoard",
]

#: independent death signals and how much each one is worth. The
#: orchestrator flag is authoritative (the process was SIGKILLed by the
#: chaos driver / supervisor — weight 2 converges alone); the soft signals
#: need a second opinion, so a lone heartbeat lapse (partition window) or
#: a lone slow round (GC pause) never evicts a live member.
EVIDENCE_WEIGHTS: Dict[str, int] = {
    "heartbeat_lapse": 1,
    "breaker_open": 1,
    "deadline_overrun": 1,
    "peer_dead_flag": 2,
}


class MeshReformError(RuntimeError):
    """The re-form ladder ran out of rungs without forming a world."""


@dataclass
class PeerEvidence:
    """Accumulated death evidence for one peer: distinct signal kinds,
    each recorded once until the peer's slate is cleared by a successful
    re-form (or a rejoin)."""

    peer: str
    kinds: Dict[str, float] = field(default_factory=dict)  # kind -> at

    def add(self, kind: str, at: float) -> bool:
        if kind not in EVIDENCE_WEIGHTS:
            raise ValueError(f"unknown evidence kind {kind!r}")
        if kind in self.kinds:
            return False
        self.kinds[kind] = at
        return True

    @property
    def score(self) -> int:
        return sum(EVIDENCE_WEIGHTS[k] for k in self.kinds)

    def snapshot(self) -> dict:
        return {"peer": self.peer, "score": self.score, "kinds": dict(self.kinds)}


class RendezvousBoard:
    """Shared-directory rendezvous: heartbeats, orchestrator flags, join
    requests, and re-form *calls*. Every write is atomic (tmp + replace,
    or O_EXCL for the single-writer call files) — the PR 15 lesson that a
    reader polling on existence must never observe a torn file."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def _put(self, name: str, payload: dict) -> None:
        path = self._path(name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fp:
            json.dump(payload, fp)
        os.replace(tmp, path)

    def _get(self, name: str) -> Optional[dict]:
        try:
            with open(self._path(name)) as fp:
                return json.load(fp)
        except (OSError, json.JSONDecodeError):
            return None

    # ---------------------------------------------------------- heartbeats
    def beat(self, member: str, at: float) -> None:
        self._put(f"hb-{member}.json", {"member": member, "at": at})

    def last_beat(self, member: str) -> Optional[float]:
        rec = self._get(f"hb-{member}.json")
        return None if rec is None else float(rec.get("at", 0.0))

    # ------------------------------------------------------ orchestrator flag
    def flag_dead(self, member: str, why: str = "") -> None:
        self._put(f"dead-{member}.json", {"member": member, "why": why})

    def dead_flagged(self, member: str) -> bool:
        return os.path.exists(self._path(f"dead-{member}.json"))

    def clear_dead_flag(self, member: str) -> None:
        try:
            os.unlink(self._path(f"dead-{member}.json"))
        except OSError:
            pass

    # ------------------------------------------------------------- joins
    def request_join(self, member: str, at: float) -> None:
        self._put(f"join-{member}.json", {"member": member, "at": at})

    def pending_joins(self) -> List[str]:
        out = []
        for name in sorted(os.listdir(self.directory)):
            if name.startswith("join-") and name.endswith(".json"):
                rec = self._get(name)
                if rec is not None:
                    out.append(rec["member"])
        return out

    def clear_join(self, member: str) -> None:
        try:
            os.unlink(self._path(f"join-{member}.json"))
        except OSError:
            pass

    # --------------------------------------------------------- telemetry
    def put_telemetry(self, member: str, payload: dict) -> None:
        """One member's latest MetricsRegistry snapshot (ISSUE 18): the
        fleet-metrics channel that keeps working through a degrade window,
        because file rendezvous needs no formed world. Atomic like every
        board write — a scraper mid-merge never reads a torn snapshot."""
        self._put(f"telemetry-{member}.json", payload)

    def read_telemetry(self) -> Dict[str, dict]:
        """member → latest snapshot payload, for the aggregating host."""
        out: Dict[str, dict] = {}
        for name in sorted(os.listdir(self.directory)):
            if name.startswith("telemetry-") and name.endswith(".json"):
                rec = self._get(name)
                if rec is not None and rec.get("member"):
                    out[rec["member"]] = rec
        return out

    # ------------------------------------------------------------- calls
    def publish_call(
        self, epoch: int, members: Sequence[str], coordinator: str
    ) -> dict:
        """Single-writer world call for one epoch: O_EXCL create, so the
        re-election race (caller-elect vs takeover) has exactly one
        winner — the loser reads the winner's call."""
        payload = {
            "epoch": epoch,
            "members": list(members),
            "coordinator": coordinator,
        }
        path = self._path(f"call-{epoch}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fp:
            json.dump(payload, fp)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            os.unlink(tmp)
            existing = self._get(f"call-{epoch}.json")
            if existing is None:
                raise  # torn loser-side read is impossible (writer is atomic)
            return existing
        os.close(fd)
        os.replace(tmp, path)
        return payload

    def read_call(self, epoch: int) -> Optional[dict]:
        rec = self._get(f"call-{epoch}.json")
        if rec is not None and "members" in rec and "coordinator" in rec:
            return rec
        return None

    def latest_call(self, min_epoch: int = 0) -> Optional[dict]:
        best: Optional[dict] = None
        for name in os.listdir(self.directory):
            if name.startswith("call-") and name.endswith(".json"):
                rec = self._get(name)
                if (
                    rec is not None
                    and rec.get("epoch", -1) >= min_epoch
                    and (best is None or rec["epoch"] > best["epoch"])
                ):
                    best = rec
        return best


class JaxWorldOps:
    """Production WorldOps: forms/detaches/tears down the real jax world
    (see :mod:`~.multihost`). ``form`` returns a
    :class:`~.multihost.MultiHostContext`."""

    def __init__(
        self,
        devices_per_host: int,
        *,
        init_timeout_s: int = 20,
        heartbeat_interval_s: int = 2,
        max_missing_heartbeats: int = 10,
    ):
        self.devices_per_host = devices_per_host
        self.init_timeout_s = init_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.max_missing_heartbeats = max_missing_heartbeats

    def form(self, members: Sequence[str], process_id: int, coordinator: str):
        from .multihost import MultiHostContext, form_world, teardown_world

        n = len(members)
        if n == 1:
            # the degrade rung: a plain local backend, no coordination
            # runtime at all (and no gloo config — the measured gotcha)
            teardown_world(rebuild_local=True)
            return MultiHostContext(
                process_id=0, n_hosts=1, devices_per_host=self.devices_per_host
            )
        form_world(
            n,
            process_id,
            coordinator,
            init_timeout_s=self.init_timeout_s,
            heartbeat_interval_s=self.heartbeat_interval_s,
            max_missing_heartbeats=self.max_missing_heartbeats,
        )
        return MultiHostContext(
            process_id=process_id,
            n_hosts=n,
            devices_per_host=self.devices_per_host,
            coordinator=coordinator,
        )

    def detach(self) -> bool:
        from .multihost import detach_world

        return detach_world()

    def teardown(self) -> None:
        from .multihost import teardown_world

        teardown_world(rebuild_local=True)


class MeshController:
    """Owns one host process's view of mesh membership end to end:
    evidence → counted degrade → coordinator re-election → re-form ladder
    → join absorption. See the module docstring for the state machine."""

    FORMING = "forming"
    SERVING = "serving"
    DEGRADED = "degraded"
    REFORMING = "reforming"

    def __init__(
        self,
        member_id: str,
        members: Sequence[str],
        board: RendezvousBoard,
        ops,
        *,
        events: Optional[ResilienceEvents] = None,
        evidence_threshold: int = 2,
        heartbeat_timeout_s: float = 5.0,
        reform_attempts: int = 6,
        backoff_base_s: float = 0.25,
        backoff_cap_s: float = 2.0,
        call_wait_s: float = 15.0,
        call_takeover_s: float = 3.0,
        poll_interval_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        pick_address: Optional[Callable[[], str]] = None,
    ):
        self.member_id = member_id
        self.members: List[str] = list(members)
        self.board = board
        self.ops = ops
        self.events = events if events is not None else global_events()
        self.evidence_threshold = evidence_threshold
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.reform_attempts = reform_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.call_wait_s = call_wait_s
        self.call_takeover_s = call_takeover_s
        self.poll_interval_s = poll_interval_s
        self._clock = clock
        self._wall = wall_clock
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        if pick_address is None:
            from .multihost import pick_coordinator

            pick_address = pick_coordinator
        self._pick_address = pick_address
        self.state = MeshController.FORMING
        self.epoch = 0
        self.world = None
        self.evidence: Dict[str, PeerEvidence] = {}
        self.degrades = 0
        self.reforms = 0
        self.joins_absorbed = 0
        self._register_epoch_gauge()

    # ------------------------------------------------------------- metrics
    def _register_epoch_gauge(self) -> None:
        from ..diagnostics.metrics import global_metrics

        reg = global_metrics()
        self._epoch_gauge = reg.gauge(
            "fusion_mesh_epoch",
            help="monotonic mesh world incarnation this host is serving in",
        )
        self._epoch_gauge.set(self.epoch)
        reg.set_aggregation("fusion_mesh_epoch", "max")

    # ------------------------------------------------------------ evidence
    def _evidence(self, peer: str) -> PeerEvidence:
        if peer not in self.evidence:
            self.evidence[peer] = PeerEvidence(peer)
        return self.evidence[peer]

    def _note(self, peer: str, kind: str) -> None:
        if self._evidence(peer).add(kind, self._clock()):
            self.events.record("mesh_evidence", f"{peer}:{kind}")

    def note_breaker_open(self, peer: str) -> None:
        self._note(peer, "breaker_open")

    def note_deadline_overrun(self, peer: str) -> None:
        self._note(peer, "deadline_overrun")

    def note_peer_dead_flag(self, peer: str) -> None:
        self._note(peer, "peer_dead_flag")

    def beat(self) -> None:
        """Publish this member's liveness on the board (wall clock — the
        board is cross-process, monotonic origins differ per reader)."""
        self.board.beat(self.member_id, self._wall())

    def poll_evidence(self) -> None:
        """One evidence sweep over the board: heartbeat lapses and
        orchestrator dead flags for every peer in the current world."""
        now = self._wall()
        for peer in self.members:
            if peer == self.member_id:
                continue
            if self.board.dead_flagged(peer):
                self.note_peer_dead_flag(peer)
            last = self.board.last_beat(peer)
            if last is not None and now - last > self.heartbeat_timeout_s:
                self._note(peer, "heartbeat_lapse")

    def dead_peers(self) -> List[str]:
        """Peers whose accumulated evidence converged past the threshold,
        in current member order."""
        return [
            m
            for m in self.members
            if m != self.member_id
            and m in self.evidence
            and self.evidence[m].score >= self.evidence_threshold
        ]

    # ------------------------------------------------------------ lifecycle
    def form_initial(self, coordinator: str) -> object:
        """First world formation at process start (launcher-provided
        coordinator, canonical member order)."""
        rank = self.members.index(self.member_id)
        self.world = self.ops.form(self.members, rank, coordinator)
        self.epoch = 1
        self._epoch_gauge.set(self.epoch)
        self.state = MeshController.SERVING
        self.beat()
        return self.world

    def adopt_world(self, world, *, epoch: int = 1) -> object:
        """Adopt an ALREADY-FORMED world (the :func:`~.multihost.
        init_multihost` bring-up path): the controller starts SERVING at
        ``epoch`` without re-forming — from here on it owns membership."""
        self.world = world
        self.epoch = epoch
        self._epoch_gauge.set(epoch)
        self.state = MeshController.SERVING
        self.beat()
        return world

    def detach(self) -> bool:
        """Retire the coordination agent once the caller has compiled its
        collective programs (blocks on the agent's own all-hosts shutdown
        barrier). Counted: this is the moment failure detection hands over
        from jax to this controller."""
        detached = bool(self.ops.detach())
        if detached:
            self.events.record("mesh_detached", f"epoch={self.epoch}")
        return detached

    def degrade(self, reason: str) -> None:
        """Counted degrade: abandon the current (possibly wedged) world
        in-process and fall to local serving. NEVER exits the process —
        the survivor keeps serving its shards between this call and the
        re-form completing."""
        self.events.record("mesh_degraded", reason)
        self.degrades += 1
        self.ops.teardown()
        self.world = None
        self.state = MeshController.DEGRADED

    def reform(self, survivors: Sequence[str]) -> object:
        """Re-form the world over ``survivors`` (canonical order) with the
        counted retry/timeout/backoff ladder on coordinator re-election."""
        survivors = list(survivors)
        if self.member_id not in survivors:
            raise ValueError(f"{self.member_id} not in survivor set {survivors}")
        self.state = MeshController.REFORMING
        last_err: Optional[Exception] = None
        for attempt in range(1, self.reform_attempts + 1):
            target = self.epoch + attempt
            self.events.record(
                "mesh_reform_attempt", f"epoch={target} attempt={attempt}"
            )
            try:
                world = self._attempt_reform(survivors, target)
            except Exception as e:  # noqa: BLE001 — every rung surfaces, counted
                last_err = e
                self.events.record(
                    "mesh_reform_failed", f"epoch={target}: {e}"
                )
                delay = min(
                    self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_cap_s
                )
                # full jitter (0.5x..1.5x): simultaneous survivors must not
                # re-collide on the board in lockstep
                self._sleep(delay * (0.5 + self._rng.random()))
                continue
            self.world = world
            self.epoch = target
            self._epoch_gauge.set(self.epoch)
            retired = [m for m in self.members if m not in survivors]
            self.members = survivors
            self.state = MeshController.SERVING
            self.reforms += 1
            # fresh slate: evidence against reformed members is stale by
            # construction (it described the PREVIOUS world)
            for m in survivors:
                self.evidence.pop(m, None)
            # retire the dropped members' clock samples with their
            # membership: the per-peer fusion_clock_* series otherwise
            # accumulate one labeled pair per ref across every re-form
            # (ISSUE 18 satellite — the cardinality leak)
            if retired:
                from ..diagnostics.clocksync import global_clock_sync

                global_clock_sync().prune(retired)
            self.events.record(
                "mesh_reform_ok", f"epoch={self.epoch} members={len(survivors)}"
            )
            self.beat()
            return world
        raise MeshReformError(
            f"re-form over {survivors} failed after {self.reform_attempts} "
            f"attempts: {last_err}"
        )

    def _attempt_reform(self, survivors: List[str], target_epoch: int) -> object:
        """One ladder rung: elect/read the call, then form. The lowest
        surviving rank publishes; higher ranks poll and TAKE OVER after a
        rank-staggered timeout (the caller-elect may be the dead one)."""
        rank = survivors.index(self.member_id)
        call: Optional[dict] = None
        if rank == 0:
            call = self.board.publish_call(
                target_epoch, survivors, self._pick_address()
            )
        else:
            deadline = self._clock() + self.call_wait_s
            takeover_at = self._clock() + self.call_takeover_s * rank
            while call is None:
                call = self.board.read_call(target_epoch)
                if call is not None:
                    break
                now = self._clock()
                if now >= deadline:
                    raise TimeoutError(
                        f"no call for epoch {target_epoch} within "
                        f"{self.call_wait_s}s"
                    )
                if now >= takeover_at:
                    self.events.record(
                        "mesh_coordinator_takeover",
                        f"epoch={target_epoch} rank={rank}",
                    )
                    call = self.board.publish_call(
                        target_epoch, survivors, self._pick_address()
                    )
                    break
                self._sleep(self.poll_interval_s)
        if sorted(call["members"]) != sorted(survivors):
            raise RuntimeError(
                f"call for epoch {target_epoch} names {call['members']}, "
                f"expected {survivors}"
            )
        return self.ops.form(
            call["members"],
            call["members"].index(self.member_id),
            call["coordinator"],
        )

    # ---------------------------------------------------------------- joins
    def pending_joins(self) -> List[str]:
        return [
            m for m in self.board.pending_joins() if m not in self.members
        ]

    def absorb_joins(self, joiners: Sequence[str]) -> object:
        """Absorb live joiners: re-form to N+k with the joiners appended in
        sorted order (every member derives the same order), then clear the
        requests. The shard rebalance onto the joiner is the caller's
        ShardMap/warm-restore step — membership is what this owns."""
        joiners = sorted(j for j in joiners if j not in self.members)
        if not joiners:
            return self.world
        new_members = self.members + joiners
        if self.state == MeshController.SERVING:
            # graceful path: the old world is healthy, tear it down cleanly
            # (counted as a degrade — serving narrows to local during the
            # re-form window, and that must never be silent)
            self.degrade(f"join-absorb:{','.join(joiners)}")
        world = self.reform(new_members)
        for j in joiners:
            self.events.record("mesh_join_absorbed", j)
            self.joins_absorbed += 1
            self.board.clear_join(j)
            self.board.clear_dead_flag(j)
        return world

    def join(self, timeout_s: float = 60.0) -> object:
        """Joiner side: request membership, then poll for the first call
        that names this member and form into it."""
        self.board.request_join(self.member_id, self._wall())
        self.state = MeshController.REFORMING
        deadline = self._clock() + timeout_s
        while True:
            call = self.board.latest_call(min_epoch=self.epoch + 1)
            if call is not None and self.member_id in call["members"]:
                world = self.ops.form(
                    call["members"],
                    call["members"].index(self.member_id),
                    call["coordinator"],
                )
                self.world = world
                self.epoch = call["epoch"]
                self._epoch_gauge.set(self.epoch)
                self.members = list(call["members"])
                self.state = MeshController.SERVING
                self.events.record("mesh_joined", f"epoch={self.epoch}")
                self.beat()
                return world
            if self._clock() >= deadline:
                raise MeshReformError(
                    f"join of {self.member_id} saw no call within {timeout_s}s"
                )
            self._sleep(self.poll_interval_s)

    # ------------------------------------------------------------- reporting
    def snapshot(self) -> dict:
        return {
            "member": self.member_id,
            "state": self.state,
            "epoch": self.epoch,
            "members": list(self.members),
            "degrades": self.degrades,
            "reforms": self.reforms,
            "joins_absorbed": self.joins_absorbed,
            "evidence": {p: e.snapshot() for p, e in self.evidence.items()},
        }
