"""DevicePlacement — the shard map's device half (ISSUE 9 tentpole).

PR 5's control plane routes *calls*: a :class:`~.shard_map.ShardMap` maps
keys → virtual shards → member processes. This module extends the SAME
epoch-versioned assignment down one more level, onto the accelerator mesh:

    virtual shard --rendezvous(member)--> member --rendezvous(device)-->
    device slot --> a fixed-width row block of the mesh-sharded CSR mirror

so a cluster member's shard-map assignment also PINS its slice of the
device graph (ISSUE 9: "retires the single-device-graph-per-hub
assumption"). The properties the routed wave kernel leans on:

- **Fixed shard geometry.** Node ids partition into V contiguous id ranges
  (``ids_per_shard``); each shard occupies ONE fixed-width device slot
  (``slot_rows``, 32-aligned for the packed frontier words). Moving a
  shard therefore moves exactly one row block — state for unmoved shards
  never relocates and never leaves the device.
- **Slot stability across epochs.** :meth:`moved_to` keeps every unmoved
  shard in its existing slot and first-fit-places only the moved shards on
  their new owner's devices. A reshard is O(moved), not O(V).
- **Determinism.** Device choice within a member is rendezvous-hashed
  (sha1, like the member assignment itself), so every process derives the
  same placement from the same ``(ShardMap, mesh shape)`` — nothing but
  the tiny ShardMap travels on the wire.

``mesh_members`` names which cluster members are co-located on THIS mesh
(ICI domain). Shards owned by members outside it have no device slot here:
their invalidations cross hosts and take the RPC relay — the DCN fallback
path (rpc/fanout.py counts it) — instead of the collective exchange.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .shard_map import ShardMap

__all__ = ["DevicePlacement", "PlacementError"]


class PlacementError(RuntimeError):
    """The placement cannot host the request (slot overflow ⇒ the caller
    rebuilds with more headroom, exactly like a mirror-patch overflow)."""


def _dev_score(member: str, device: int, shard: int) -> int:
    digest = hashlib.sha1(f"{member}|dev{device}|{shard}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


@dataclass
class DevicePlacement:
    """One epoch of shard → device-slot assignment for a node capacity.

    Mutable ONLY through :meth:`moved_to` (which returns a new placement
    sharing geometry) — the arrays themselves are the routed graph's
    layout contract and are treated as frozen once a graph is built."""

    shard_map: ShardMap
    n_dev: int
    n_nodes: int
    #: members co-located on this mesh, in DEVICE ORDER: member i owns the
    #: contiguous device range [i*dpm, (i+1)*dpm)
    mesh_members: Tuple[str, ...]
    ids_per_shard: int = 0
    slot_rows: int = 0
    slots_per_dev: int = 0
    #: the HOST axis (ISSUE 15): host h owns the contiguous device range
    #: [h*devices_per_host, (h+1)*devices_per_host) — cluster/multihost.py
    #: verifies this against the real process layout at bring-up. 0 means
    #: single host (every device local), the pre-multihost default.
    devices_per_host: int = 0
    #: shard → owning device (-1: owner member is off-mesh → DCN relay)
    shard_dev: np.ndarray = field(default_factory=lambda: np.empty(0, np.int32))
    #: shard → slot index on its device (-1 when off-mesh)
    shard_slot: np.ndarray = field(default_factory=lambda: np.empty(0, np.int32))
    moves: int = 0  # cumulative device-shard moves along this lineage

    # ------------------------------------------------------------------ build
    @staticmethod
    def build(
        shard_map: ShardMap,
        n_dev: int,
        n_nodes: int,
        mesh_members: Optional[Sequence[str]] = None,
        slot_headroom: float = 1.5,
        devices_per_host: Optional[int] = None,
    ) -> "DevicePlacement":
        """Derive the placement for a map + mesh. ``mesh_members`` defaults
        to ALL members (single-host cluster: the whole map lives on this
        mesh). ``slot_headroom`` over-provisions per-device slots so a
        reshard can first-fit moved shards without a rebuild.
        ``devices_per_host`` declares the host axis (default: all devices
        one host) — the hierarchical exchange and host-aware reshard move
        costs key off it."""
        members = tuple(mesh_members) if mesh_members is not None else shard_map.members
        if not members:
            raise PlacementError("placement needs at least one mesh member")
        if n_dev < len(members) or n_dev % len(members):
            raise PlacementError(
                f"{n_dev} devices do not split evenly over {len(members)} mesh members"
            )
        dph = n_dev if not devices_per_host else int(devices_per_host)
        if dph <= 0 or n_dev % dph:
            raise PlacementError(
                f"{n_dev} devices do not split into {devices_per_host}-device hosts"
            )
        V = shard_map.n_shards
        ids_per_shard = max(-(-n_nodes // V), 1)
        slot_rows = max((ids_per_shard + 31) // 32 * 32, 32)
        p = DevicePlacement(
            shard_map=shard_map,
            n_dev=n_dev,
            n_nodes=n_nodes,
            mesh_members=members,
            ids_per_shard=ids_per_shard,
            slot_rows=slot_rows,
            shard_dev=np.full(V, -1, np.int32),
            shard_slot=np.full(V, -1, np.int32),
            devices_per_host=dph,
        )
        member_set = set(members)
        dpm = n_dev // len(members)
        member_devs = {m: range(i * dpm, (i + 1) * dpm) for i, m in enumerate(members)}
        # deterministic slot fill: device choice is rendezvous-hashed per
        # (member, device, shard); slots fill in shard order
        next_slot = np.zeros(n_dev, np.int64)
        assignment = shard_map.assignment
        for s in range(V):
            owner = assignment[s] if assignment else None
            if owner not in member_set:
                continue  # off-mesh: the DCN relay owns this shard's traffic
            dev = max(member_devs[owner], key=lambda d: _dev_score(owner, d, s))
            p.shard_dev[s] = dev
            p.shard_slot[s] = next_slot[dev]
            next_slot[dev] += 1
        peak = int(next_slot.max()) if n_dev else 0
        p.slots_per_dev = max(int(np.ceil(peak * slot_headroom)), peak, 1)
        return p

    # ------------------------------------------------------------------ geometry
    @property
    def n_local(self) -> int:
        return self.slots_per_dev * self.slot_rows

    @property
    def n_global(self) -> int:
        return self.n_dev * self.n_local

    @property
    def epoch(self) -> int:
        return self.shard_map.epoch

    @property
    def n_hosts(self) -> int:
        dph = self.devices_per_host or self.n_dev
        return self.n_dev // dph

    def host_of_device(self, dev: int) -> int:
        return int(dev) // (self.devices_per_host or self.n_dev)

    def cross_host_moves(self, moves: Sequence[Tuple[int, int, int]]) -> int:
        """How many of a :meth:`moved_to` move list's row-block transfers
        cross a host boundary — the DCN leg of a reshard (the host-aware
        candidate ranking exists to minimize this)."""
        return sum(
            1
            for _s, old, new in moves
            if old >= 0 and new >= 0 and self.host_of_device(old) != self.host_of_device(new)
        )

    def shard_of_node(self, node_id: int) -> int:
        return int(node_id) // self.ids_per_shard

    def member_of_device(self, dev: int) -> str:
        dpm = self.n_dev // len(self.mesh_members)
        return self.mesh_members[dev // dpm]

    def on_mesh(self, shard: int) -> bool:
        return bool(self.shard_dev[shard] >= 0)

    def row_of_shard(self, shard: int) -> int:
        """First global row of a shard's device slot."""
        dev = int(self.shard_dev[shard])
        if dev < 0:
            raise PlacementError(f"shard {shard} is off-mesh (DCN-relayed)")
        return dev * self.n_local + int(self.shard_slot[shard]) * self.slot_rows

    def permutation(self) -> Tuple[np.ndarray, np.ndarray]:
        """``(perm, inv)``: node id → global device row, and row → node id
        (-1 on pad / off-mesh rows). Vectorized over all on-mesh shards."""
        perm = np.full(self.n_nodes, -1, np.int64)
        inv = np.full(self.n_global, -1, np.int64)
        V = self.shard_map.n_shards
        for s in range(V):
            if self.shard_dev[s] < 0:
                continue
            lo = s * self.ids_per_shard
            hi = min(lo + self.ids_per_shard, self.n_nodes)
            if hi <= lo:
                continue
            base = self.row_of_shard(s)
            rows = np.arange(base, base + (hi - lo), dtype=np.int64)
            perm[lo:hi] = rows
            inv[rows] = np.arange(lo, hi, dtype=np.int64)
        return perm, inv

    # ------------------------------------------------------------------ reshard
    def moved_to(
        self, new_map: ShardMap, mesh_members: Optional[Sequence[str]] = None
    ) -> Tuple["DevicePlacement", List[Tuple[int, int, int]]]:
        """The next placement for ``new_map``, keeping every unmoved shard
        in its current slot. Returns ``(placement, moves)`` where each move
        is ``(shard, old_dev, new_dev)`` (old_dev/new_dev may be -1 for a
        shard entering/leaving this mesh). Raises :class:`PlacementError`
        when a destination device has no free slot — the caller rebuilds
        the routed graph from scratch (counted, never silent)."""
        members = tuple(mesh_members) if mesh_members is not None else self.mesh_members
        if not members or self.n_dev % len(members):
            raise PlacementError("mesh membership changed shape; rebuild required")
        # member → device ranges re-derive for the NEW member set (a kill
        # hands the departed member's devices to the survivors; a join
        # carves ranges back out). Unmoved shards keep their existing
        # device slots regardless — the ranges steer only moved shards, so
        # a membership change moves exactly the diff'd shards' row blocks.
        nxt = DevicePlacement(
            shard_map=new_map,
            n_dev=self.n_dev,
            n_nodes=self.n_nodes,
            mesh_members=members,
            ids_per_shard=self.ids_per_shard,
            slot_rows=self.slot_rows,
            slots_per_dev=self.slots_per_dev,
            shard_dev=self.shard_dev.copy(),
            shard_slot=self.shard_slot.copy(),
            moves=self.moves,
            devices_per_host=self.devices_per_host,
        )
        member_set = set(members)
        dpm = self.n_dev // len(members)
        dph = self.devices_per_host or self.n_dev
        member_devs = {m: range(i * dpm, (i + 1) * dpm) for i, m in enumerate(members)}
        moved = sorted(ShardMap.diff(self.shard_map, new_map))
        moved_set = set(moved)
        assignment = new_map.assignment
        # occupancy per device, from the carried slots
        used: Dict[int, set] = {d: set() for d in range(self.n_dev)}
        for s in range(new_map.n_shards):
            if nxt.shard_dev[s] >= 0 and s not in moved_set:
                used[int(nxt.shard_dev[s])].add(int(nxt.shard_slot[s]))

        def ranked(owner: str, s: int, old_dev: int) -> List[int]:
            """The new owner's devices in preference order: rendezvous
            score descending, SAME-HOST candidates first when the shard
            already has rows resident (ISSUE 15 satellite: a reshard must
            not needlessly turn an intra-host slot reassignment into a
            cross-host DCN transfer)."""
            devs = sorted(
                member_devs[owner], key=lambda d: _dev_score(owner, d, s), reverse=True
            )
            if old_dev < 0 or dph >= self.n_dev:
                return devs
            oh = old_dev // dph
            return [d for d in devs if d // dph == oh] + [
                d for d in devs if d // dph != oh
            ]

        moves: List[Tuple[int, int, int]] = []
        # pass 1: a moved shard whose PREFERRED device equals its old one
        # keeps its slot outright — no row block moves, but its slot must
        # be claimed before pass 2 first-fits genuinely moving shards
        cands: Dict[int, List[int]] = {}
        for s in moved:
            owner = assignment[s] if assignment else None
            if owner not in member_set:
                cands[s] = []
                continue
            cands[s] = ranked(owner, s, int(nxt.shard_dev[s]))
            if cands[s][0] == int(nxt.shard_dev[s]):
                used[cands[s][0]].add(int(nxt.shard_slot[s]))
        for s in moved:
            old_dev = int(nxt.shard_dev[s])
            devs = cands[s]
            if not devs:
                nxt.shard_dev[s] = -1
                nxt.shard_slot[s] = -1
                if old_dev >= 0:
                    moves.append((s, old_dev, -1))
                continue
            if devs[0] == old_dev:
                continue  # ownership changed hands, the rows never move
            # scan the ranked candidates for the first with a free slot
            # (landing back on old_dev keeps the rows in place)
            placed = False
            for dev in devs:
                if dev == old_dev and int(nxt.shard_slot[s]) not in used[dev]:
                    used[dev].add(int(nxt.shard_slot[s]))
                    placed = True
                    break
                slot = next(
                    (k for k in range(self.slots_per_dev) if k not in used[dev]), None
                )
                if slot is not None:
                    used[dev].add(slot)
                    nxt.shard_dev[s] = dev
                    nxt.shard_slot[s] = slot
                    moves.append((s, old_dev, dev))
                    placed = True
                    break
            if not placed:
                raise PlacementError(
                    f"no free slot on any of member {assignment[s]!r}'s devices "
                    f"for moved shard {s} (slots_per_dev={self.slots_per_dev})"
                )
        nxt.moves = self.moves + len(moves)
        return nxt, moves

    def snapshot(self) -> dict:
        on_mesh = int((self.shard_dev >= 0).sum())
        return {
            "epoch": self.epoch,
            "n_dev": self.n_dev,
            "hosts": self.n_hosts,
            "devices_per_host": self.devices_per_host or self.n_dev,
            "mesh_members": list(self.mesh_members),
            "ids_per_shard": self.ids_per_shard,
            "slot_rows": self.slot_rows,
            "slots_per_dev": self.slots_per_dev,
            "shards_on_mesh": on_mesh,
            "shards_off_mesh": self.shard_map.n_shards - on_mesh,
            "moves": self.moves,
        }
