"""explain(key) — causal-chain introspection (ISSUE 4 tentpole).

Answers the operator's second question: *why is this key stale, who
invalidated it, and did my clients get fenced*. ``explain`` joins four
sources on the cause id PR 3 threads through the system:

- the **flight recorder** (``flight_recorder.RECORDER``): the key's
  lifecycle events (registered / computed / invalidated / fenced), each
  stamped with cause id + wave seq + oplog index where known;
- the **wave profiler** ring (``TpuGraphBackend.profiler``): the wave
  record the cause names — kind, seeds, newly count, device/apply ms;
- the **tracing span buffer**: span-shaped causes resolve back to the
  originating command/replay span (an ``oplog:replay`` span carries the
  oplog entry index — the "via oplog entry E on host H" link);
- the **fence events**: how many client subscriptions the invalidation
  pushed through ``$sys-c``.

Cross-peer: a client's key is served by its server — the ``$sys-d``
diagnostics service ships an explain request ``[service, method, args]``
to the peer and returns the server-assembled chain
(:func:`explain_remote` / :func:`explain_client`); install both ends with
:func:`install_explain`. Fused, deferred execution is exactly where
per-op behavior disappears (the FuseFlow / nonblocking-GraphBLAS papers
in PAPERS.md motivate introspection for fused dataflow) — this module is
the "why" half of the observability stack.

Everything returned is a JSON-safe dict: it travels verbatim through
``GET /explain?key=`` on the HTTP gateway and the ``$sys-d`` wire codec.

Imports from ``core``/``rpc`` are function-local: ``diagnostics`` is
imported by ``core.computed`` at module scope, so this module must not
close the cycle.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Any, List, Optional

from .flight_recorder import RECORDER, FlightRecorder, call_key, method_key_fragment
from .tracing import find_span_by_cause

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "explain",
    "explain_with_fallback",
    "explain_remote",
    "explain_client",
    "install_explain",
]


def _resolve(key: Any, hub) -> tuple:
    """``(key_str, computed_or_None)`` for a Computed, a ComputedInput, or
    a key string (matched against the hub registry's input reprs).

    The string path is bounded at :data:`MAX_REGISTRY_SCAN` nodes: a repr
    per registry entry is an O(graph) Python pass, and a live 10M-node hub
    must not stall its event loop on one ``GET /explain`` — past the cap
    the journal (bounded ring) is the only string resolver, which still
    answers the chain for any recently-active key."""
    from ..core.computed import Computed
    from ..core.inputs import ComputedInput

    if isinstance(key, Computed):
        return repr(key.input), key
    if isinstance(key, ComputedInput):
        return repr(key), key.get_existing_computed()
    key_str = str(key)
    if hub is not None and len(hub.registry) <= MAX_REGISTRY_SCAN:
        registry = hub.registry
        with registry._lock:
            items = list(registry._map.items())
        for input, ref in items:
            if repr(input) == key_str:
                return key_str, ref()
    return key_str, None


MAX_REGISTRY_SCAN = 100_000  # string-key resolution cap; see _resolve


def _rec_covers_seq(rec: dict, wave: int) -> bool:
    """Does this profiler record describe wave ``wave``? Exact seq match,
    or — for a physically-fused chain — any seq inside the record's
    ``seq_span`` (one seq per logical wave, ISSUE 7)."""
    if rec["seq"] == wave:
        return True
    span = rec.get("seq_span")
    return span is not None and span[0] <= wave <= span[1]


def explain(
    key: Any,
    hub=None,
    backend=None,
    recorder: Optional[FlightRecorder] = None,
    max_events: int = 64,
) -> dict:
    """Assemble the causal chain for ``key``.

    Returns a JSON-safe dict: ``key``, ``state`` (live consistency state
    when the node resolves), ``events`` (the flight-journal tail for the
    key), ``invalidation`` (cause id, the wave record, the originating
    span, the oplog entry, clients fenced) and ``chain`` — the
    human-readable lines ("X invalidated by wave W, caused by command C
    via oplog entry E on host H, fenced N clients")."""
    recorder = recorder if recorder is not None else RECORDER
    key_str, computed = _resolve(key, hub)
    if backend is None and hub is not None:
        backend = hub.graph_backend

    keys = [key_str]
    call = getattr(computed, "call", None)  # ClientComputed: fence events
    if call is not None:  # are journaled under the call-shaped key
        keys.append(call_key(call.service, call.method, call.args))
    events: List[dict] = []
    for k in keys:
        events.extend(recorder.for_key(k, limit=max_events))
    events.sort(key=lambda e: e["seq"])
    events = events[-max_events:]

    out: dict = {
        "key": key_str,
        "state": None,
        "events": events,
        "invalidation": None,
        "chain": [],
    }
    if computed is not None:
        out["state"] = computed.consistency_state.name
        out["version"] = computed.version.format()

    # lazy-pending takes PRECEDENCE over the journal: a device wave marked
    # the node's pending bit but the host has not materialized it (that
    # happens on next read) — the wave's identity is not recorded per-node,
    # only the bit (graph/backend.py two-tier apply). Journal events for
    # this key belong to a PRIOR generation of it; attributing the current
    # invalidation to them would name the wrong wave.
    from ..core.consistency import ConsistencyState

    if (
        computed is not None
        and computed._state == ConsistencyState.CONSISTENT
        and computed._pending_probe()
    ):
        out["invalidation"] = {"cause": None, "pending": True}
        out["chain"] = [
            f"{key_str}: invalidated by a device wave (lazy tier — the "
            f"cause materializes when the node is next read or observed)"
        ]
        return out

    # the most recent invalidation's identifiers: the live stamp first
    # (survives ring eviction), the journal tail as the fallback.
    # ClientComputed carries its cause on the bound call (the
    # invalidation_cause property); plain Computeds on the slot.
    cause = wave = oplog = None
    inv_event = None
    if computed is not None and computed.is_invalidated:
        cause = (
            getattr(computed, "invalidation_cause", None)
            or computed._invalidation_cause
        )
    for e in reversed(events):
        if e["kind"] in ("invalidated", "fenced", "client_fenced"):
            if (
                cause is not None
                and e.get("cause") is not None
                and e.get("cause") != cause
            ):
                # a PRIOR generation's event (this key's current
                # invalidation has a different live cause stamp — its own
                # event was evicted or recorded while disabled): harvesting
                # wave/oplog from it would pin the wrong wave record
                continue
            inv_event = e
            cause = cause if cause is not None else e.get("cause")
            wave = e.get("wave")
            oplog = e.get("oplog")
            break
    if cause is None and inv_event is None:
        if computed is not None and computed.is_invalidated:
            # invalidated, but neither a live stamp nor a journal event
            # survived (ring eviction, or the recorder was disabled)
            out["invalidation"] = {"cause": None}
            out["chain"] = [
                f"{key_str}: invalidated, cause unknown (journal evicted "
                f"or recorder disabled)"
            ]
        else:
            state = out["state"] or "unknown"
            out["chain"] = [f"{key_str}: no recorded invalidation (state: {state})"]
        return out

    # reshard cause family (ISSUE 5): the key was fenced because its shard
    # moved to a new owner — no wave, no span, no oplog entry; the story is
    # the epoch change. The rebalancer journals a per-key "resharded" event
    # whose detail names the owner move, so the chain can say exactly
    # where the key's subscription went.
    if cause is not None and cause.startswith("reshard:"):
        epoch_s = cause.partition(":")[2]
        # match the journal event to THIS invalidation's epoch: after
        # consecutive reshards the key's newest "resharded" event can
        # describe a later epoch's owner move, not the one that fenced it
        moved_ev = next(
            (
                e
                for e in reversed(events)
                if e["kind"] == "resharded" and e.get("cause") == cause
            ),
            None,
        )
        detail = (moved_ev or {}).get("detail") or ""
        line = f"{key_str}: invalidated by reshard to epoch {epoch_s}"
        if "owner " in detail:
            line += f" ({detail[detail.index('owner '):].replace('->', '→')})"
        out["invalidation"] = {
            "cause": cause,
            "reshard_epoch": int(epoch_s) if epoch_s.isdigit() else epoch_s,
            "detail": detail or None,
        }
        out["chain"] = [
            line,
            f"caused by {cause}",
            "the fenced client re-subscribes on the new owner at its next read",
        ]
        return out

    # drain cause family (ISSUE 12c): the frame was not an invalidation at
    # all — an edge node draining for a rolling deploy hinted this session
    # to reconnect, carrying its resume token; nothing upstream changed
    # and resume replay covers any fence that lands during the gap
    if cause is not None and cause.startswith("drain:"):
        edge_name = cause.partition(":")[2]
        out["invalidation"] = {"cause": cause, "drain_edge": edge_name}
        out["chain"] = [
            f"{key_str}: session hinted to reconnect — edge '{edge_name}' "
            f"drained (rolling deploy)",
            f"caused by {cause}",
            "the client resumes elsewhere with the carried token; "
            "latest-wins replay covers anything fenced during the gap "
            "(zero deliveries lost)",
        ]
        return out

    # wave record: an exact seq match wins outright (several waves can
    # share one span-shaped cause — e.g. two cascades under one command
    # span — and a cause-first scan would grab the NEWEST of them, not the
    # one that actually invalidated this key); a logical wave physically
    # FUSED into a chain has no record of its own — any seq inside a
    # record's seq_span resolves to the fused record (ISSUE 7), with the
    # logical wave still named by its own seq in the chain text; cause
    # matching is only the fallback for events that carried no seq
    wave_rec = None
    profiler = getattr(backend, "profiler", None)
    if profiler is not None:
        recs = profiler.recent()
        if wave is not None:
            wave_rec = next(
                (r for r in reversed(recs) if _rec_covers_seq(r, wave)), None
            )
        if wave_rec is None and wave is None and cause is not None:
            wave_rec = next((r for r in reversed(recs) if r["cause"] == cause), None)

    span_dict = None
    oplog_batch_upto = None
    if cause is not None:
        span = find_span_by_cause(cause)
        if span is not None:
            span_dict = span.to_dict()
            if oplog is None and span.source == "oplog":
                if span.name == "replay":
                    idx = span.tags.get("index")
                    if isinstance(idx, int):
                        oplog = idx
                elif span.name == "batch":
                    # a lane-burst covers SEVERAL oplog records; the span
                    # carries only the batch's watermark bound — report it
                    # as a bound, never as "the" entry (it usually isn't)
                    upto = span.tags.get("upto")
                    if isinstance(upto, int):
                        oplog_batch_upto = upto

    fence_events = recorder.for_cause(cause, kind="client_fenced") if cause else []
    # per-KEY count in the per-key report; the wave-wide total rides
    # beside it explicitly — reporting the wave total as "this key's
    # subscribers" misled exactly the incident reader this exists for
    clients_fenced = sum(
        e.get("count", 1) for e in fence_events if e.get("key") in keys
    )
    wave_clients_fenced = sum(e.get("count", 1) for e in fence_events)
    # the edge hop (ISSUE 8): an EdgeNode on this process journals one
    # "edge_fenced" per re-fanned key with the count of downstream
    # sessions — the chain then spans server wave → edge → session
    edge_events = recorder.for_cause(cause, kind="edge_fenced") if cause else []
    edge_sessions_fenced = sum(
        e.get("count", 1) for e in edge_events if e.get("key") in keys
    )
    wave_edge_sessions_fenced = sum(e.get("count", 1) for e in edge_events)

    host = cause.split("/", 1)[0] if cause and "/" in cause else None
    out["invalidation"] = {
        "cause": cause,
        "host": host,
        "wave": wave_rec,
        # the LOGICAL wave's seq when the event recorded one (a fused
        # record's own seq is just the chain head — naming it here would
        # misattribute every non-head wave in the chain)
        "wave_seq": (
            wave if wave is not None
            else (wave_rec["seq"] if wave_rec is not None else None)
        ),
        "span": span_dict,
        "oplog": oplog,
        "clients_fenced": clients_fenced,
        "wave_clients_fenced": wave_clients_fenced,
    }
    if edge_events:
        out["invalidation"]["edge_sessions_fenced"] = edge_sessions_fenced
        out["invalidation"]["wave_edge_sessions_fenced"] = wave_edge_sessions_fenced
    if oplog_batch_upto is not None:
        out["invalidation"]["oplog_batch_upto"] = oplog_batch_upto

    from ..core.computed import LAZY_WAVE_DETAIL

    chain: List[str] = []
    inv_detail = (inv_event.get("detail") or "") if inv_event is not None else ""
    if wave_rec is not None:
        span = wave_rec.get("seq_span")
        if (
            span is not None
            and wave is not None
            and wave_rec.get("fused_depth", 1) > 1
        ):
            # the LOGICAL wave keeps its own name even though it was
            # physically fused — the operator greps for "wave#<seq>" and
            # must land on the chain that actually ran it
            chain.append(
                f"{key_str} invalidated by wave #{wave} (physically fused "
                f"into chain #{span[0]}–#{span[1]}, depth "
                f"{wave_rec['fused_depth']}, {wave_rec['kind']}: "
                f"{wave_rec['seeds']} seed(s), {wave_rec['newly']} newly "
                f"invalid across the chain)"
            )
        else:
            chain.append(
                f"{key_str} invalidated by wave #{wave_rec['seq']} "
                f"({wave_rec['kind']}, {wave_rec['seeds']} seed(s), "
                f"{wave_rec['newly']} newly invalid)"
            )
    elif wave is not None:
        chain.append(f"{key_str} invalidated by wave #{wave}")
    elif inv_detail == LAZY_WAVE_DETAIL:
        # a materialized lazy-tier invalidation: the mechanism WAS a device
        # wave even though its identity was never recorded per-node —
        # claiming "host-led" here would misdirect the runbook (exact
        # constant compare, never prose parsing)
        chain.append(
            f"{key_str} invalidated by a device wave "
            f"(materialized lazily — wave identity not recorded per-node)"
        )
    elif cause is not None and "/wave#" in cause:
        # a wave-SHAPED cause with no local wave record: this process is
        # the CLIENT end (no profiler here) — the wave ran on the peer
        # that minted the cause; "host-led" would contradict the cause id
        # printed on the next line
        chain.append(
            f"{key_str} invalidated by a device wave on a remote peer "
            f"(the cause's host — ask it via explain_remote/$sys-d)"
        )
    else:
        chain.append(f"{key_str} invalidated (host-led, no device wave)")
    mesh_info = wave_rec.get("mesh") if wave_rec is not None else None
    if mesh_info is not None:
        # ISSUE 9: the shard hop, named. The frontier crossed device
        # shards INSIDE the wave (mesh collectives) — the ~80 ms per-key
        # host-relay hop this line used to imply is gone for on-mesh keys.
        line = (
            f"cross-shard frontier exchanged on-mesh via {mesh_info['exchange']} "
            f"collectives ({mesh_info['levels']} level(s) over "
            f"{mesh_info['n_dev']} devices, placement epoch "
            f"{mesh_info['epoch']}) — no host-relay hop"
        )
        # place THIS key's device shard when the backend can
        entry = getattr(backend, "_routed_mirror", None) if backend is not None else None
        nid = backend.id_for(computed) if (backend is not None and computed is not None) else None
        if entry is not None and nid is not None:
            pl = entry["graph"].placement
            shard = pl.shard_of_node(nid)
            if pl.on_mesh(shard):
                dev = int(pl.shard_dev[shard])
                line += (
                    f"; key's device shard #{shard} lives on device {dev} "
                    f"(member {pl.member_of_device(dev)})"
                )
        chain.append(line)
        # ISSUE 18: the straggler, named. When the wave's cause has trace
        # segments, the stitched timeline knows which (host, shard) paced
        # the worst merge epoch — the line that turns "the wave was slow"
        # into a rebalance target.
        if cause is not None:
            from .mesh_telemetry import global_mesh_trace

            stitched = global_mesh_trace().stitch(cause)
            if stitched is not None and stitched.get("paced_by"):
                p = stitched["paced_by"]
                line = (
                    f"paced by host {p['host']} shard {p['shard']} at level "
                    f"{p['level']} ({p['stall_ms']:.1f} ms stall across "
                    f"{len(stitched['hosts'])} host(s)"
                )
                if stitched["partial"]:
                    line += (
                        f"; PARTIAL — no segments from "
                        f"{','.join(stitched['missing_hosts'])}"
                    )
                chain.append(line + ")")
    if cause is not None:
        line = f"caused by {cause}"
        if host is not None:
            line += f" on host {host}"
        chain.append(line)
        # ISSUE 20: the cluster commander labels command causes (and the
        # oplog reader re-labels them on replay hosts), so the chain names
        # the WRITE end to end: command → wave seq → delivery
        from .mesh_telemetry import global_mesh_trace

        command_label = global_mesh_trace().command_for(cause)
        if command_label is not None:
            chain.append(f"invalidated by command {command_label}")
    if span_dict is not None:
        chain.append(
            f"originating span: {span_dict['source']}:{span_dict['name']}"
            f"#{span_dict['span_id']}"
        )
    if oplog is not None:
        chain.append(f"via oplog entry {oplog}")
    elif oplog_batch_upto is not None:
        chain.append(f"via an oplog replay batch (entries up to {oplog_batch_upto})")
    if clients_fenced:
        line = f"fenced {clients_fenced} client subscription(s) on this key"
        if wave_clients_fenced > clients_fenced:
            line += f" ({wave_clients_fenced} across the wave)"
        chain.append(line)
    elif wave_clients_fenced:
        chain.append(
            f"the wave fenced {wave_clients_fenced} client subscription(s) "
            f"(none recorded on this key)"
        )
    if edge_sessions_fenced:
        line = (
            f"edge re-fanned to {edge_sessions_fenced} downstream session(s) "
            f"on this key"
        )
        if wave_edge_sessions_fenced > edge_sessions_fenced:
            line += f" ({wave_edge_sessions_fenced} across the wave)"
        # the value-plane rung that produced the fanned value (ISSUE 11):
        # the edge stamps "value served from wave block / batched re-read /
        # per-key re-read" into its journal detail — surface it so an
        # operator can see WHICH upstream path a fence actually took
        for e in edge_events:
            detail = e.get("detail") or ""
            if e.get("key") in keys and "value served from" in detail:
                line += f" ({detail[detail.index('value served from'):]})"
                break
        chain.append(line)
    elif wave_edge_sessions_fenced:
        chain.append(
            f"the edge re-fanned {wave_edge_sessions_fenced} downstream "
            f"session(s) (none recorded on this key)"
        )
    # the overload plane (ISSUE 12): sheds journaled against this key —
    # an operator asking "why is this subscriber not seeing updates" gets
    # told the edge turned its attaches away, and why
    shed_events = [e for e in events if e.get("kind") == "edge_shed"]
    if shed_events:
        reasons: dict = {}
        for e in shed_events:
            detail = e.get("detail") or ""
            reason = (
                detail.split("reason=", 1)[1].split()[0]
                if "reason=" in detail
                else "?"
            )
            reasons[reason] = reasons.get(reason, 0) + 1
        chain.append(
            "the edge SHED "
            + ", ".join(f"{n}× {r}" for r, n in sorted(reasons.items()))
            + " attach(es) naming this key (counted in "
            "fusion_edge_shed_total; clients retry per Retry-After)"
        )
    # workload attribution (ISSUE 19): is this key a tracked heavy hitter?
    # The hot-key board answers with its rank and share per domain — the
    # line that turns "this key is slow" into "this key is 3.1% of all
    # edge deliveries". Checked against the delivery and invalidation
    # sketches; the node-id sketch needs the backend to resolve the key.
    from .hotkeys import global_hotkeys

    board = global_hotkeys()
    hot: list = []
    share = board.share_of("edge_deliveries", key_str)
    if share is not None:
        hot.append(share)
    nid = (
        backend.id_for(computed)
        if (backend is not None and computed is not None)
        else None
    )
    if nid is not None:
        share = board.share_of("wave_invalidations", str(nid))
        if share is not None:
            hot.append(share)
    if hot:
        out["hotkeys"] = hot
        top = max(hot, key=lambda h: h["share"])
        chain.append(
            f"key is a top-k heavy hitter: {top['share'] * 100:.1f}% of "
            f"{top['domain']} (rank {top['rank']}, ~{top['count']} offers, "
            f"over-count ≤ {top['error']})"
        )
    out["chain"] = chain
    return out


def explain_with_fallback(
    key: Any, hub=None, recorder: Optional[FlightRecorder] = None
) -> dict:
    """:func:`explain`, falling back to the journal's FRAGMENT matcher when
    an exact lookup finds nothing — an operator pasting a partial key still
    gets the chain. THE shared resolution used by both operator entry
    points (``GET /explain?key=`` and the ``$sys-d`` string path), so the
    two never drift."""
    rec = recorder if recorder is not None else RECORDER
    report = explain(key, hub=hub, recorder=rec)
    if report.get("state") is None and not report.get("events"):
        matches = rec.keys_matching(str(key), limit=1)
        if matches:
            report = explain(matches[0], hub=hub, recorder=rec)
    return report


# ---------------------------------------------------------------- $sys-d hop


def install_explain(rpc_hub, fusion_hub=None, recorder: Optional[FlightRecorder] = None):
    """Install the ``$sys-d`` diagnostics endpoint on an RPC hub — both the
    server side (answers ``explain`` requests against ``fusion_hub``'s
    registry and this process's flight recorder) and the client side
    (resolves ``explain_result`` replies for :func:`explain_remote`).
    Idempotent; returns the hub.

    Exposure note: the endpoint answers ANY connected peer, so it serves
    only ``[service, method, args]`` requests — shapes the peer could
    invoke as calls anyway; free-form journal scans stay behind the HTTP
    route's proxy-trust gate (see ``_serve_explain``)."""
    pending = getattr(rpc_hub, "_explain_pending", None)
    if pending is None:
        pending = rpc_hub._explain_pending = {}

    async def handler(peer, message) -> None:
        from ..rpc.message import DIAG_SYSTEM_SERVICE, RpcMessage
        from ..utils.serialization import dumps, loads

        if message.method == "explain":
            # every failure class up to the send itself must still produce
            # an error REPLY (the documented contract): a malformed request
            # or a non-serializable report dying in this detached task
            # would otherwise park the asker for its full timeout
            try:
                (req,) = loads(message.argument_data)
                report = await _serve_explain(rpc_hub, fusion_hub, recorder, req)
            except Exception as e:  # noqa: BLE001
                report = {"error": f"{type(e).__name__}: {e}"}
            try:
                payload = dumps([report])
            except Exception as e:  # noqa: BLE001 — a repr slipped something
                payload = dumps([{"error": f"report not serializable: {e}"}])
            await peer.send(
                RpcMessage(
                    0,
                    message.call_id,
                    DIAG_SYSTEM_SERVICE,
                    "explain_result",
                    payload,
                )
            )
        elif message.method == "explain_result":
            # keyed by (peer, call_id): call ids are PER-PEER counters, so
            # two peers of one hub can both allocate id 7 concurrently
            fut = pending.pop((id(peer), message.call_id), None)
            if fut is not None and not fut.done():
                fut.set_result(loads(message.argument_data)[0])

    rpc_hub.diag_system_handler = handler
    return rpc_hub


async def _serve_explain(rpc_hub, fusion_hub, recorder, req) -> dict:
    """Server-side resolution — ``[service, method, args]`` triples ONLY:
    the triple peeks the live computed through the service registry (never
    computing — ``get_existing``), so a peer learns exactly about call
    shapes it could invoke anyway. Bare-string requests are REFUSED: a
    free-form fragment scan over the process-wide journal would disclose
    other tenants' key reprs (embedded call args included) to any
    connected peer — the HTTP route gates that behind proxy trust, and the
    RPC hop must not be the ungated back door. Failures travel as
    ``{"error": ...}`` payloads, never as a torn link."""
    try:
        if isinstance(req, (list, tuple)) and len(req) == 3:
            from ..utils.serialization import deep_tuple

            service, method, args = req
            # args must re-tuple DEEPLY before replay or the interned
            # cache key is unhashable
            args = deep_tuple(args)
            computed = None
            explainable = False
            try:
                from ..core.context import get_existing

                service_def = rpc_hub.service_registry.require(service)
                m = service_def.method(method)
                # ONLY compute methods may be peeked: the GET_EXISTING
                # flag is honored by the @compute_method wrapper alone —
                # a plain RPC method (a mutation!) would EXECUTE outright
                # as a side effect of an introspection request
                if getattr(m.fn, "__compute_method_def__", None) is not None:
                    explainable = True
                    computed = await get_existing(lambda: m.fn(*args))
            except Exception:  # noqa: BLE001 — treated as not-explainable below
                log.debug("explain: registry peek failed for %s.%s", service, method)
            if computed is not None:
                return explain(computed, hub=computed._hub(), recorder=recorder)
            if not explainable:
                # an unresolvable triple must NOT degrade into a journal
                # scan: the fragment match ignores the service name, so a
                # peer probing a made-up service would read lifecycle
                # metadata of keys it cannot invoke (the auditor's private
                # canary included)
                return {
                    "error": f"{service}.{method} is not an explainable "
                    f"compute method on this hub"
                }
            # node collected (or never computed here): the journal may still
            # remember it — match by the method+args fragment of the key
            frag = method_key_fragment(method, args)
            return explain_with_fallback(frag, hub=fusion_hub, recorder=recorder)
        return {
            "error": "explain over $sys-d requires [service, method, args]; "
            "free-form key strings are served only by the trust-gated "
            "HTTP /explain route"
        }
    except Exception as e:  # noqa: BLE001 — introspection must never throw on the pump
        log.exception("explain request failed")
        return {"error": f"{type(e).__name__}: {e}"}


async def explain_remote(peer, service: str, method: str, args, timeout: float = 5.0) -> dict:
    """Ask a PEER who killed a key: ships ``[service, method, args]`` over
    ``$sys-d.explain`` and awaits the server-assembled chain. Requires
    :func:`install_explain` on the asking hub (and on the serving hub)."""
    from ..rpc.message import DIAG_SYSTEM_SERVICE, RpcMessage
    from ..utils.serialization import dumps

    pending = getattr(peer.hub, "_explain_pending", None)
    if pending is None or peer.hub.diag_system_handler is None:
        raise RuntimeError("install_explain(rpc_hub) must run before explain_remote")
    call_id = peer.allocate_call_id()
    fut: asyncio.Future = asyncio.get_event_loop().create_future()
    pending[(id(peer), call_id)] = fut
    try:
        await peer.when_connected()
        await peer.send(
            RpcMessage(
                0,
                call_id,
                DIAG_SYSTEM_SERVICE,
                "explain",
                dumps([[service, method, list(args)]]),
            )
        )
        return await asyncio.wait_for(fut, timeout)
    finally:
        pending.pop((id(peer), call_id), None)


async def explain_client(node, timeout: float = 5.0) -> dict:
    """Both ends of a ClientComputed's story: the LOCAL fence record (this
    process's journal) and the SERVER's causal chain over the ``$sys-d``
    hop — "my key was fenced by call #C" joined to "wave W caused it"."""
    call = node.call
    if call is None:
        raise ValueError(f"{node!r} has no live call (cache-only node)")
    input = node.input
    local = explain(node, hub=node._hub())
    remote = await explain_remote(
        call.peer, input.function_ref.service, input.method, input.args, timeout
    )
    return {"local": local, "remote": remote}
