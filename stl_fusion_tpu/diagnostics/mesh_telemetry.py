"""Mesh-wide observability (ISSUE 18): fleet telemetry aggregation +
cross-host wave trace stitching + straggler attribution.

Every diagnostics mechanism before this PR was process-local: the metrics
registry answers ``GET /metrics`` for ONE host, the span ring and flight
recorder hold ONE host's events, and ``explain()`` can only name what its
own process saw. On a multi-host mesh that leaves the operator with N
scrapes to join by hand and NO way to answer "where did wave X's exchange
levels spend time, per host" — the question the async frontier plane
(ISSUE 17) exists to make interesting.

Three pieces, all transport-agnostic (the mesh control plane — rpc/tcp.py
frames while serving, the rendezvous board during degrade — carries plain
dict payloads):

* :class:`MeshTelemetryPublisher` — periodically snapshots the LOCAL
  ``MetricsRegistry`` into a flat ``{series: value}`` payload (histograms
  ship ``_sum``/``_count``), stamped with a ``(wall_ts, perf_ts)`` clock
  pair and the registry's declared-MAX names, plus this host's recent
  wave trace segments.

* :class:`MeshTelemetryAggregator` — keeps the latest snapshot per host
  and renders ONE merged Prometheus exposition: per-series merge is SUM
  by default and MAX for declared-MAX gauges (the same contract
  ``MetricsRegistry.set_aggregation`` enforces within a process), every
  contributing series is re-emitted labeled ``host="h<N>"``, and a
  snapshot older than two reporting periods — or from an evicted member —
  is EXCLUDED from the merge but marked ``fusion_mesh_telemetry_stale``
  (its last-known per-host series stay visible): stale data is flagged,
  never silently merged and never silently dropped. The local host's
  series are read live at merge time, so the answering host is always
  fresh. The membership arc (``fusion_mesh_epoch``, degrade/re-form
  counters) rides the ordinary series, so a host kill stays visible
  through the scrape.

* :class:`MeshTraceStore` — bounded per-cause store of
  :class:`WaveSegment` records. The routed wave path records segments at
  its HOST-VISIBLE boundaries (dispatch → harvest); the wave kernel
  itself runs inside one jit/shard_map program, so per-level host
  timestamps do not exist — per-level segments are DERIVED by dividing
  the measured host window across the counted levels (totals and
  ordering preserved; documented, not hidden). Cross-host alignment is
  real: ``stitch()`` maps every remote segment through
  ``ClockSync.to_local`` (residual bounded by the recorded RTT/2) and
  returns one timeline with per-level stall attribution, the pacing
  ``(host, shard)`` named per merge epoch, and a straggler table. A host
  that never reported yields a PARTIAL stitch — counted
  (``fusion_mesh_trace_partial_stitches_total``), never silent.

Package constraint: ``core.computed`` imports ``diagnostics`` at module
scope, so nothing here may import ``core``/``rpc`` at module scope (the
RPC-facing :class:`MeshTelemetryService` is plain duck-typing) — and jax
is only touched lazily inside :func:`local_host`.
"""
from __future__ import annotations

import contextvars
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set

from .clocksync import ClockSync, global_clock_sync, now
from .metrics import MetricsRegistry, global_metrics

__all__ = [
    "WaveSegment",
    "MeshTraceStore",
    "MeshTelemetryPublisher",
    "MeshTelemetryAggregator",
    "MeshTelemetryService",
    "global_mesh_trace",
    "local_host",
    "set_dispatch_cause",
    "reset_dispatch_cause",
    "current_dispatch_cause",
]

#: segment phases the routed wave / super-round path records — the five
#: host-attributable stations of one async wave (ISSUE 18 tentpole b)
PHASES = ("spec_expand", "a2a", "exchange", "tree_round", "quiescence_vote", "fence_drain")

_host_cache: Optional[str] = None


def local_host() -> str:
    """This process's mesh host name (``h<process_index>``): the label
    every locally recorded segment and series carries."""
    global _host_cache
    if _host_cache is None:
        idx = 0
        try:  # lazy: diagnostics must import without jax on the path
            import jax

            idx = jax.process_index()
        except Exception:  # noqa: BLE001 — no jax runtime: single host
            idx = 0
        _host_cache = f"h{idx}"
    return _host_cache


#: cause id the super-round threads through the routed dispatch so every
#: host-boundary segment of one wave shares the wave's EXISTING cause id
#: (never a second identity minted per layer)
_dispatch_cause: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "fusion_mesh_dispatch_cause", default=None
)


def set_dispatch_cause(cause: Optional[str]):
    return _dispatch_cause.set(cause)


def reset_dispatch_cause(token) -> None:
    _dispatch_cause.reset(token)


def current_dispatch_cause() -> Optional[str]:
    return _dispatch_cause.get()


@dataclass
class WaveSegment:
    """One host's span of one wave phase, in the HOST-LOCAL perf_counter
    timeline (alignment happens at stitch time, where the clock table is)."""

    cause: str
    host: str
    phase: str
    level: int  # merge-epoch index within the wave; -1 = wave-scoped
    shard: int  # pacing shard within the host; -1 = not attributed
    t0: float
    t1: float

    def to_dict(self) -> dict:
        return {
            "cause": self.cause, "host": self.host, "phase": self.phase,
            "level": self.level, "shard": self.shard,
            "t0": self.t0, "t1": self.t1,
        }


_SEGMENT_KEYS = ("cause", "host", "phase", "level", "shard", "t0", "t1")

#: fleet-plane meta series the aggregator owns: rendered once from LIVE
#: state at the top of the mesh exposition, never re-merged from snapshots
#: (a remote host's view of staleness is not THIS scrape's view)
_META_BASES = frozenset(
    {"fusion_mesh_telemetry_stale", "fusion_mesh_telemetry_hosts_reporting"}
)


class MeshTraceStore:
    """Bounded per-cause segment store (FlightRecorder discipline: one
    lock, insertion-ordered eviction, counted drops, an ``enabled`` gate
    so the hot path costs one attribute read when tracing is off)."""

    def __init__(self, max_causes: int = 256, max_segments_per_cause: int = 512):
        self.enabled = True
        self.max_causes = max_causes
        self.max_segments_per_cause = max_segments_per_cause
        self._lock = threading.Lock()
        #: cause -> list of segment dicts, insertion-ordered for eviction
        self._by_cause: "OrderedDict[str, List[dict]]" = OrderedDict()
        #: cause -> originating-command label (ISSUE 20): bounded the same
        #: way, fed by the cluster commander locally and by the oplog
        #: reader for operations journaled on other hosts
        self._commands: "OrderedDict[str, str]" = OrderedDict()
        self.recorded = 0
        self.ingested = 0
        self.dropped = 0

    # ------------------------------------------------------------------ write
    def record(
        self,
        cause: Optional[str],
        phase: str,
        t0: float,
        t1: float,
        host: Optional[str] = None,
        level: int = -1,
        shard: int = -1,
    ) -> None:
        if not self.enabled or cause is None:
            return
        seg = {
            "cause": cause, "host": host or local_host(), "phase": phase,
            "level": int(level), "shard": int(shard),
            "t0": float(t0), "t1": float(t1),
        }
        if self._append(seg):
            self.recorded += 1
            global_metrics().counter(
                "fusion_mesh_trace_segments_total",
                help="per-host wave trace segments recorded at the routed "
                "path's host-visible boundaries (ISSUE 18)",
            ).inc()

    def ingest(self, segments: Iterable[dict]) -> int:
        """Store segments shipped from another host VERBATIM (still on the
        remote clock — ``stitch`` aligns; storing aligned values would bake
        in whatever offset estimate existed at arrival time)."""
        n = 0
        for raw in segments or ():
            try:
                seg = {k: raw[k] for k in _SEGMENT_KEYS}
                seg["level"] = int(seg["level"])
                seg["shard"] = int(seg["shard"])
                seg["t0"] = float(seg["t0"])
                seg["t1"] = float(seg["t1"])
            except (KeyError, TypeError, ValueError):
                continue  # malformed remote segment: skip, never poison
            if self._append(seg, dedup=True):
                n += 1
        self.ingested += n
        return n

    def _append(self, seg: dict, dedup: bool = False) -> bool:
        with self._lock:
            bucket = self._by_cause.get(seg["cause"])
            if bucket is None:
                bucket = self._by_cause[seg["cause"]] = []
            self._by_cause.move_to_end(seg["cause"])
            if len(bucket) >= self.max_segments_per_cause:
                self.dropped += 1
                return False
            if dedup and seg in bucket:
                return False  # periodic snapshots re-ship recent segments
            bucket.append(seg)
            while len(self._by_cause) > self.max_causes:
                self._by_cause.popitem(last=False)
        return True

    # ------------------------------------------------------------- attribution
    def note_command(self, cause: Optional[str], label: str) -> None:
        """Remember which command a wave cause id originated from, so a
        stitched timeline (and ``explain()``) can say "invalidated by
        command X" instead of only naming an opaque cause (ISSUE 20).
        First write wins: the origin member labels before any replayer."""
        if cause is None or not label:
            return
        with self._lock:
            if cause not in self._commands:
                self._commands[cause] = label
                while len(self._commands) > self.max_causes:
                    self._commands.popitem(last=False)

    def command_for(self, cause: Optional[str]) -> Optional[str]:
        if cause is None:
            return None
        with self._lock:
            return self._commands.get(cause)

    # ------------------------------------------------------------------ read
    def causes(self) -> List[str]:
        with self._lock:
            return list(self._by_cause)

    def latest_cause(self) -> Optional[str]:
        with self._lock:
            return next(reversed(self._by_cause), None)

    def segments_for(self, cause: str) -> List[dict]:
        with self._lock:
            return list(self._by_cause.get(cause, ()))

    def export_recent(self, host: Optional[str] = None, max_causes: int = 8) -> List[dict]:
        """The last ``max_causes`` causes' segments (optionally one host's
        only — what a publisher ships: each host ships what IT measured)."""
        with self._lock:
            recent = list(self._by_cause)[-max_causes:]
            segs = [dict(s) for c in recent for s in self._by_cause[c]]
        if host is not None:
            segs = [s for s in segs if s["host"] == host]
        return segs

    def clear(self) -> None:
        with self._lock:
            self._by_cause.clear()
            self._commands.clear()
        self.recorded = 0
        self.ingested = 0
        self.dropped = 0

    # ------------------------------------------------------------------ stitch
    def stitch(
        self,
        cause: str,
        clock: Optional[ClockSync] = None,
        expected_hosts: Optional[Sequence[str]] = None,
        local: Optional[str] = None,
    ) -> Optional[dict]:
        """ONE timeline for one wave: every remote segment mapped onto the
        local clock (``ClockSync.to_local`` — residual ≤ recorded RTT/2),
        per-level stall attribution, the pacing (host, shard) named per
        merge epoch, and a straggler table. ``None`` when the cause was
        never seen; a PARTIAL stitch (``expected_hosts`` not all present)
        is counted and flagged, never silent."""
        segs = self.segments_for(cause)
        if not segs:
            return None
        clock = clock or global_clock_sync()
        local = local or local_host()
        aligned = []
        for s in segs:
            if s["host"] == local:
                a0, a1 = s["t0"], s["t1"]
            else:
                a0 = clock.to_local(s["host"], s["t0"])
                a1 = clock.to_local(s["host"], s["t1"])
            aligned.append({**s, "a0": a0, "a1": a1})
        aligned.sort(key=lambda s: (s["a0"], s["a1"], s["host"], s["level"]))
        origin = min(s["a0"] for s in aligned)
        t_end = max(s["a1"] for s in aligned)
        hosts = sorted({s["host"] for s in aligned})
        missing = sorted(set(expected_hosts or ()) - set(hosts))
        partial = bool(missing)
        reg = global_metrics()
        reg.counter(
            "fusion_mesh_trace_stitches_total",
            help="stitched cross-host wave timelines assembled (ISSUE 18)",
        ).inc()
        if partial:
            reg.counter(
                "fusion_mesh_trace_partial_stitches_total",
                help="stitches missing at least one expected host's segments "
                "(counted PARTIAL, never a silent single-host timeline)",
            ).inc()

        def rel(ts: float) -> float:
            return round((ts - origin) * 1e3, 3)

        # per merge epoch: the level's end on each host; the stall is the
        # spread between the first and last host to finish the level, and
        # the pacer is the (host, shard) of the latest-finishing segment
        by_level: Dict[int, List[dict]] = {}
        for s in aligned:
            if s["level"] >= 0:
                by_level.setdefault(s["level"], []).append(s)
        levels = []
        for lvl in sorted(by_level):
            group = by_level[lvl]
            host_end = {}
            for s in group:
                host_end[s["host"]] = max(host_end.get(s["host"], s["a1"]), s["a1"])
            pacer = max(group, key=lambda s: (s["a1"], s["host"]))
            stall_ms = 0.0
            if len(host_end) > 1:
                stall_ms = round((max(host_end.values()) - min(host_end.values())) * 1e3, 3)
            levels.append({
                "level": lvl,
                "start_ms": rel(min(s["a0"] for s in group)),
                "end_ms": rel(max(s["a1"] for s in group)),
                "stall_ms": stall_ms,
                "hosts": len(host_end),
                "paced_by": {"host": pacer["host"], "shard": pacer["shard"]},
            })
        straggler: Dict[tuple, dict] = {}
        for entry in levels:
            key = (entry["paced_by"]["host"], entry["paced_by"]["shard"])
            row = straggler.setdefault(
                key,
                {"host": key[0], "shard": key[1], "paced_levels": 0, "stall_ms_total": 0.0},
            )
            row["paced_levels"] += 1
            row["stall_ms_total"] = round(row["stall_ms_total"] + entry["stall_ms"], 3)
        straggler_rows = sorted(
            straggler.values(),
            key=lambda r: (-r["stall_ms_total"], -r["paced_levels"], r["host"], r["shard"]),
        )
        paced_by = None
        if levels:
            worst = max(levels, key=lambda e: (e["stall_ms"], e["level"]))
            paced_by = {
                "host": worst["paced_by"]["host"],
                "shard": worst["paced_by"]["shard"],
                "level": worst["level"],
                "stall_ms": worst["stall_ms"],
            }
        clock_table = {}
        for h in hosts:
            off, rtt = clock.offset(h), clock.rtt(h)
            clock_table[h] = {
                "offset_ms": None if off is None else round(off * 1e3, 3),
                "rtt_ms": None if rtt is None else round(rtt * 1e3, 3),
                # identity-mapped hosts (local / never probed) carry no
                # alignment error of their own
                "residual_ms": 0.0 if (h == local or rtt is None) else round(rtt * 5e2, 3),
            }
        out = {
            "cause": cause,
            "hosts": hosts,
            "partial": partial,
            "missing_hosts": missing,
            "duration_ms": rel(t_end),
            "clock": clock_table,
            "segments": [
                {
                    "host": s["host"], "phase": s["phase"], "level": s["level"],
                    "shard": s["shard"], "start_ms": rel(s["a0"]), "end_ms": rel(s["a1"]),
                }
                for s in aligned
            ],
            "levels": levels,
            "straggler": straggler_rows,
            "paced_by": paced_by,
        }
        command = self.command_for(cause)
        if command is not None:
            out["command"] = command
        return out


_TRACE: Optional[MeshTraceStore] = None
_TRACE_LOCK = threading.Lock()


def global_mesh_trace() -> MeshTraceStore:
    global _TRACE
    if _TRACE is None:
        with _TRACE_LOCK:
            if _TRACE is None:
                _TRACE = MeshTraceStore()
    return _TRACE


# ---------------------------------------------------------------------- fleet
class MeshTelemetryPublisher:
    """One host's side of the fleet plane: flatten the local registry into
    a transport-agnostic payload and push it — to the rendezvous board
    (:meth:`publish_board`, the channel that survives degrade) or over the
    rpc/tcp control plane (:meth:`publish_hub`)."""

    def __init__(
        self,
        member: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        period_s: float = 2.0,
        trace: Optional[MeshTraceStore] = None,
        max_segment_causes: int = 8,
        slo_engine: Optional[Any] = None,
        hotkeys: Optional[Any] = None,
    ):
        self.member = member or local_host()
        self.registry = registry or global_metrics()
        self.period_s = float(period_s)
        self.trace = trace or global_mesh_trace()
        self.max_segment_causes = max_segment_causes
        self.published = 0
        self.slo_engine = slo_engine
        self.hotkeys = hotkeys

    def _health(self) -> Optional[dict]:
        """This host's local SLO verdict, evaluated at publish time so the
        aggregator's mesh merge is at most one period behind. A publisher
        over a private registry (tests emulating a remote host) gets its
        own engine; the global-registry publisher shares the process one."""
        engine = self.slo_engine
        if engine is None:
            from .slo import SloEngine, global_slo_engine

            if self.registry is global_metrics():
                engine = global_slo_engine()
            else:
                engine = SloEngine(registry=self.registry, hotkeys=self.hotkeys)
            self.slo_engine = engine
        try:
            return engine.evaluate()
        except Exception:  # noqa: BLE001 — a judging fault must not stop telemetry
            return None

    def _sketches(self) -> dict:
        board = self.hotkeys
        if board is None:
            from .hotkeys import global_hotkeys

            board = self.hotkeys = global_hotkeys()
        try:
            return board.payload()
        except Exception:  # noqa: BLE001
            return {}

    def payload(self) -> dict:
        return {
            "member": self.member,
            "period_s": self.period_s,
            # the clock pair lets an aggregator that never ran a $sys
            # probe seed a coarse wall-clock alignment (refined — never
            # displaced — by genuine min-RTT probes)
            "wall_ts": time.time(),
            "perf_ts": now(),
            "series": self.registry.flat_samples(),
            "max_names": self.registry.max_aggregated_names(),
            "segments": self.trace.export_recent(
                host=self.member, max_causes=self.max_segment_causes
            ),
            # ISSUE 19: the judgment plane rides the same snapshot — the
            # host's local SLO verdict and its heavy-hitter sketches
            "health": self._health(),
            "sketches": self._sketches(),
        }

    def _count(self) -> None:
        self.published += 1
        global_metrics().counter(
            "fusion_mesh_telemetry_snapshots_total",
            help="local MetricsRegistry snapshots published onto the mesh "
            "control plane (board file or rpc/tcp frame — ISSUE 18)",
        ).inc()

    def publish_board(self, board) -> dict:
        """Atomic board-file publish (``RendezvousBoard.put_telemetry``) —
        the degrade-window path: file rendezvous needs no mesh."""
        payload = self.payload()
        board.put_telemetry(self.member, payload)
        self._count()
        return payload

    async def publish_hub(self, hub, peer_ref: Optional[str] = None,
                          service: str = "mesh-telemetry") -> dict:
        """Push one snapshot over the rpc control plane (a length-prefixed
        rpc/tcp frame when the hub's connector is ``tcp_client_connector``)."""
        payload = self.payload()
        reply = await hub.call(service, "publish", (payload,), peer_ref=peer_ref)
        self._count()
        return reply


class MeshTelemetryService:
    """RPC-facing ingest endpoint: ``hub.add_service("mesh-telemetry",
    MeshTelemetryService(aggregator))`` on the host that answers
    ``GET /metrics?scope=mesh``."""

    def __init__(self, aggregator: "MeshTelemetryAggregator"):
        self.aggregator = aggregator

    async def publish(self, payload: dict) -> dict:
        self.aggregator.ingest(payload)
        return {"ok": True, "hosts": self.aggregator.known_hosts()}


class MeshTelemetryAggregator:
    """Latest-snapshot-per-host table + the honest merge. Register once on
    the answering host; ``render_mesh_prometheus()`` backs
    ``GET /metrics?scope=mesh``."""

    def __init__(
        self,
        local_member: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        period_s: float = 2.0,
        clock: Optional[ClockSync] = None,
        trace: Optional[MeshTraceStore] = None,
        slo_engine: Optional[Any] = None,
        hotkeys: Optional[Any] = None,
    ):
        self.local_member = local_member or local_host()
        self.registry = registry or global_metrics()
        self.period_s = float(period_s)
        self.clock = clock or global_clock_sync()
        self.trace = trace or global_mesh_trace()
        self.slo_engine = slo_engine
        self.hotkeys = hotkeys
        self._lock = threading.Lock()
        self._snaps: Dict[str, dict] = {}
        self._received: Dict[str, float] = {}
        self._evicted: Set[str] = set()
        self.merges = 0
        self.registry.register_collector(self, MeshTelemetryAggregator._collect_metrics)
        self.registry.set_aggregation("fusion_mesh_telemetry_hosts_reporting", "max")

    def _collect_metrics(self) -> dict:
        """Stale markers surface in the LOCAL scrape too (same values the
        merged exposition carries) — an operator watching plain /metrics
        sees the fleet plane's health without asking for scope=mesh."""
        stale = self.stale_hosts()
        out = {
            "fusion_mesh_telemetry_hosts_reporting": float(
                len(self.fresh_hosts())
            ),
        }
        for h in self.known_hosts():
            out[f'fusion_mesh_telemetry_stale{{host="{h}"}}'] = 1.0 if h in stale else 0.0
        return out

    # ------------------------------------------------------------------ intake
    def ingest(self, payload: dict) -> None:
        member = payload.get("member")
        if not member:
            return
        with self._lock:
            self._snaps[member] = payload
            self._received[member] = time.time()
            # a flapped member that reports again is live again — evicted
            # status describes membership, and membership changed
            self._evicted.discard(member)
        self._seed_clock(member, payload)
        self.trace.ingest(payload.get("segments") or ())

    def _seed_clock(self, member: str, payload: dict) -> None:
        """Coarse wall-clock seed for a host no $sys probe ever measured:
        without SOME offset estimate, stitch falls to the identity map and
        cross-host order is garbage. The synthetic sample carries a
        deliberately pessimistic 50 ms RTT, so any genuine min-RTT probe
        immediately replaces it."""
        if member == self.local_member or self.clock.offset(member) is not None:
            return
        wall, perf = payload.get("wall_ts"), payload.get("perf_ts")
        if wall is None or perf is None:
            return
        t = now()
        remote_now_est = float(perf) + max(time.time() - float(wall), 0.0)
        self.clock.note_sample(member, t - 0.025, remote_now_est, t + 0.025)

    def sync_board(self, board) -> List[str]:
        """Pull every member's latest board telemetry file (the standing
        degrade-window channel) into the table."""
        seen = []
        for member, payload in board.read_telemetry().items():
            self.ingest(payload)
            seen.append(member)
        return sorted(seen)

    def mark_evicted(self, member: str) -> None:
        with self._lock:
            self._evicted.add(member)

    def note_members(self, members: Sequence[str]) -> None:
        """Reconcile with the controller's membership: anything we hold a
        snapshot for that the mesh no longer names is evicted (stale by
        membership, not just by age)."""
        live = set(members)
        with self._lock:
            for m in list(self._snaps):
                if m != self.local_member and m not in live:
                    self._evicted.add(m)

    # ------------------------------------------------------------------ state
    def known_hosts(self) -> List[str]:
        with self._lock:
            return sorted({self.local_member, *self._snaps, *self._evicted})

    def stale_hosts(self, now_wall: Optional[float] = None) -> Set[str]:
        now_wall = time.time() if now_wall is None else now_wall
        with self._lock:
            out = {
                m
                for m, at in self._received.items()
                if m != self.local_member and now_wall - at > 2.0 * self.period_s
            }
            out |= {m for m in self._evicted if m != self.local_member}
        return out

    def fresh_hosts(self, now_wall: Optional[float] = None) -> List[str]:
        stale = self.stale_hosts(now_wall)
        return [h for h in self.known_hosts() if h not in stale]

    # ------------------------------------------------------------------ merge
    def _per_host_series(self) -> Dict[str, Dict[str, float]]:
        per_host = {self.local_member: self.registry.flat_samples()}
        with self._lock:
            snaps = dict(self._snaps)
        for m, payload in snaps.items():
            if m == self.local_member:
                continue  # the answering host reads itself live
            series = payload.get("series") or {}
            per_host[m] = {
                k: float(v) for k, v in series.items() if isinstance(v, (int, float))
            }
        return per_host

    def _max_bases(self) -> Set[str]:
        bases = set(self.registry.max_aggregated_names())
        with self._lock:
            for payload in self._snaps.values():
                bases.update(payload.get("max_names") or ())
        return bases

    def merged_samples(self, now_wall: Optional[float] = None):
        """``(per_host, merged, stale)``: the merge covers FRESH hosts only
        — SUM by default, MAX for any base a contributing host declared
        MAX (two hosts each 5 ms behind are 5 ms behind, not 10)."""
        per_host = self._per_host_series()
        stale = self.stale_hosts(now_wall)
        max_bases = self._max_bases()
        merged: Dict[str, float] = {}
        for host in sorted(per_host):
            if host in stale:
                continue
            for k, v in per_host[host].items():
                base = k.partition("{")[0]
                if k in merged and base in max_bases:
                    merged[k] = max(merged[k], v)
                elif k in merged:
                    merged[k] += v
                else:
                    merged[k] = v
        return per_host, merged, stale

    def render_mesh_prometheus(self, now_wall: Optional[float] = None) -> str:
        """The ``scope=mesh`` exposition: merged series first (the fleet
        answer), then every host's contributing series labeled
        ``host="h<N>"`` (stale hosts keep their LAST-KNOWN labeled series —
        flagged by the stale gauge, never dropped). Labeled families get
        one ``# TYPE <base> gauge`` line, same discipline as the registry's
        own labeled-collector rendering."""
        per_host, merged, stale = self.merged_samples(now_wall)
        self.merges += 1
        global_metrics().counter(
            "fusion_mesh_telemetry_merges_total",
            help="mesh-scope merged expositions served (GET /metrics?scope=mesh)",
        ).inc()
        lines: List[str] = []
        typed: Set[str] = set()

        def emit(key: str, value: float) -> None:
            base = key.partition("{")[0]
            if base not in typed:
                lines.append(f"# TYPE {base} gauge")
                typed.add(base)
            lines.append(f"{key} {value}")

        hosts_known = self.known_hosts()
        emit(
            "fusion_mesh_telemetry_hosts_reporting",
            float(len([h for h in hosts_known if h not in stale])),
        )
        for h in hosts_known:
            emit(f'fusion_mesh_telemetry_stale{{host="{h}"}}', 1.0 if h in stale else 0.0)
        for k in sorted(merged):
            if k.partition("{")[0] in _META_BASES:
                continue  # emitted authoritatively above, from LIVE state
            emit(k, merged[k])
        for host in sorted(per_host):
            for k in sorted(per_host[host]):
                if 'host="' in k or k.partition("{")[0] in _META_BASES:
                    continue  # already host-scoped / the fleet-plane meta
                if k.endswith("}"):
                    labeled = f'{k[:-1]},host="{host}"}}'
                else:
                    labeled = f'{k}{{host="{host}"}}'
                emit(labeled, per_host[host][k])
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------------ judgment
    def _local_engine(self):
        engine = self.slo_engine
        if engine is None:
            from .slo import SloEngine, global_slo_engine

            if self.registry is global_metrics():
                engine = global_slo_engine()
            else:
                engine = SloEngine(registry=self.registry, hotkeys=self.hotkeys)
            self.slo_engine = engine
        return engine

    def _local_board(self):
        board = self.hotkeys
        if board is None:
            from .hotkeys import global_hotkeys

            board = self.hotkeys = global_hotkeys()
        return board

    def mesh_health(self, now_wall: Optional[float] = None) -> dict:
        """The mesh-scope verdict behind ``GET /health``: the local engine
        evaluates live, every fresh remote contributes the verdict it
        shipped in its snapshot, and every stale/evicted host contributes
        a **degraded** entry — a host we cannot see is never healthy."""
        local = self._local_engine().evaluate()
        stale = self.stale_hosts(now_wall)
        with self._lock:
            remotes = {
                m: (snap.get("health") if isinstance(snap, dict) else None)
                for m, snap in self._snaps.items()
                if m != self.local_member
            }
        from .slo import merge_verdicts

        return merge_verdicts(
            local, remotes, sorted(stale), local_member=self.local_member
        )

    def merged_sketches(self, now_wall: Optional[float] = None) -> dict:
        """Per-domain heavy-hitter sketches folded across the local board
        and every FRESH remote snapshot (stale sketches would attribute a
        past workload to the present — excluded, same rule as series)."""
        from .hotkeys import HotKeyBoard

        stale = self.stale_hosts(now_wall)
        with self._lock:
            payloads = [
                snap.get("sketches")
                for m, snap in sorted(self._snaps.items())
                if m != self.local_member and m not in stale
                and isinstance(snap, dict)
            ]
        return HotKeyBoard.merge_payloads(
            [self._local_board().payload()] + [p for p in payloads if p]
        )

    def hotkeys_report(self, n: int = 5, now_wall: Optional[float] = None) -> dict:
        """Mesh-scope top-k per domain — the ``GET /hotkeys`` body."""
        merged = self.merged_sketches(now_wall)
        return {
            "scope": "mesh",
            "hosts": self.fresh_hosts(now_wall),
            "domains": {
                d: {"total": sk.total, "top": sk.topk(n)}
                for d, sk in sorted(merged.items())
            },
        }

    def summary(self, now_wall: Optional[float] = None) -> dict:
        now_wall = time.time() if now_wall is None else now_wall
        stale = self.stale_hosts(now_wall)
        with self._lock:
            ages = {
                m: round(now_wall - at, 3) for m, at in self._received.items()
            }
            evicted = sorted(self._evicted)
        return {
            "local": self.local_member,
            "hosts": self.known_hosts(),
            "fresh": [h for h in self.known_hosts() if h not in stale],
            "stale": sorted(stale),
            "evicted": evicted,
            "period_s": self.period_s,
            "snapshot_age_s": ages,
            "merges": self.merges,
        }
