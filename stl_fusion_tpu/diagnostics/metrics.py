"""Process-wide metrics registry + wave profiler (ISSUE 3 tentpole).

The single telemetry sink the rest of the system reports through: the
analogue of the reference hanging ``Meter``/``ActivitySource`` instances off
every component (src/Stl/Diagnostics/, SURVEY §5.1) — counters, gauges and
bounded log-scale histograms live HERE, with ``snapshot()`` for in-process
consumers (``FusionMonitor.report()``, bench records) and
``render_prometheus()`` for the ``/metrics`` route on the HTTP gateway.

Design rules, in tension and resolved as follows:

- **Hot paths keep their plain attribute counters** (``PeerOutbox.stats()``,
  ``ComputeFanoutIndex``, backend ``waves_run``): a registry hop per send
  would tax the exact paths the perf PRs fight for. Components instead
  register a *collector* — a cheap pull-time function the registry invokes
  only when someone actually snapshots/scrapes. Collectors are held through
  a weakref to their owner, so a dead hub/reader/breaker silently drops out
  instead of pinning itself (the FusionMonitor.dispose() lesson).
- **Histograms are bounded log-scale buckets** (powers of two between a
  floor and a ceiling): a flapping peer or a 10M-wave storm can record
  forever without growing memory, and p50/p99 estimates come from the
  cumulative bucket counts — the system reports its own latency
  distribution instead of leaving it to a bespoke harness
  (perf/fanout_path.py measured delivery p50/p99 from the outside; the
  ``fusion_e2e_delivery_ms`` histogram is the same number measured from
  the inside).
- **Values summed across collectors**: many live RpcHubs (tests, one hub
  per client) report the same metric name; the scrape shows the process
  total, matching Prometheus counter semantics.

``WaveProfiler`` is the per-wave timeline recorder ``TpuGraphBackend``
drives: a ring buffer of wave records (seed count, newly size, device vs
host milliseconds, journal depth pre/post coalescing, cause id) queryable
via ``FusionMonitor.report()["waves"]`` and dumped by bench.py — the
per-stage pipeline telemetry the streaming-dataflow papers (PAPERS.md)
lean on to find fusion-boundary stalls.
"""
from __future__ import annotations

import bisect
import itertools
import math
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "WaveProfiler",
    "global_metrics",
    "next_wave_seq",
]


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*.

    A ``{label="value"}`` suffix is preserved verbatim (the cluster router
    exports per-peer series like ``fusion_routed_calls_total{peer="m0"}``);
    only the metric-name prefix is sanitized. Suffix values come from
    in-repo collectors, never from wire input."""
    if "{" in name and name.endswith("}"):
        base, _, labels = name.partition("{")
        return _sanitize(base) + "{" + labels
    out = []
    for i, ch in enumerate(name):
        ok = ch.isascii() and (ch.isalpha() or ch == "_" or ch == ":" or (ch.isdigit() and i > 0))
        out.append(ch if ok else "_")
    return "".join(out) or "_"


class Counter:
    """Monotonic counter. ``inc()`` is a plain float add — cheap enough for
    warm paths; the HOT paths (per-send, per-wave) keep attribute counters
    and report through collectors instead."""

    kind = "counter"
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Point-in-time value: ``set()`` or a pull-time callback ``fn``."""

    kind = "gauge"
    __slots__ = ("name", "help", "_value", "fn")

    def __init__(self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self._value = 0.0
        self.fn = fn

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:  # noqa: BLE001 — a dying callback must not kill a scrape
                return float("nan")
        return self._value

    def snapshot(self) -> float:
        return self.value


class Histogram:
    """Bounded log-scale histogram: bucket edges are ``lo * 2^k`` up to
    ``hi`` plus +inf — ~26 buckets cover µs..minute at millisecond units.
    Percentiles interpolate within the winning bucket (log-midpoint for
    the overflow bucket), which is exactly as honest as the bucket width;
    the raw bucket counts travel in ``snapshot()`` so nothing is hidden."""

    kind = "histogram"
    __slots__ = ("name", "help", "unit", "edges", "buckets", "count", "sum",
                 "min", "max", "ex_cap", "exemplars", "ex_recorded", "ex_evicted")

    #: exemplar ring bound — big enough to name several distinct causes in
    #: the tail, small enough that a million-sample storm stays O(1) memory
    EXEMPLAR_CAP = 8

    def __init__(self, name: str, help: str = "", unit: str = "ms",
                 lo: float = 0.001, hi: float = 120_000.0):
        self.name = name
        self.help = help
        self.unit = unit
        edges: List[float] = []
        edge = lo
        while edge <= hi:
            edges.append(edge)
            edge *= 2.0
        self.edges = edges  # upper bounds; final +inf bucket is implicit
        self.buckets = [0] * (len(edges) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        # tail exemplars (ISSUE 19): cause-carrying samples, highest values
        # kept — an alert on this histogram links to /trace?cause= in one hop
        self.ex_cap = self.EXEMPLAR_CAP
        self.exemplars: List[list] = []
        self.ex_recorded = 0
        self.ex_evicted = 0

    def record(self, value: float, cause: Optional[str] = None) -> None:
        self.record_many(value, 1, cause)

    def record_many(self, value: float, n: int, cause: Optional[str] = None) -> None:
        """``n`` samples of the same value in one bucket update — the edge
        fan-out records one client-visible instant for a whole batch of
        synchronous-sink sessions (a per-session record() there would put
        a registry histogram inside a million-iteration loop). The single-
        sample :meth:`record` delegates here so the clamp + bucket logic
        exists once. ``cause`` (the wave cause id) offers the sample to the
        bounded exemplar ring — the tail keeps its provenance."""
        if n <= 0:
            return
        v = float(value)
        if v < 0.0 or v != v:  # clock skew / NaN: clamp, never throw
            v = 0.0
        self.buckets[bisect.bisect_left(self.edges, v)] += n
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if cause is not None:
            self._offer_exemplar(v, cause)

    def _offer_exemplar(self, v: float, cause: Any) -> None:
        """Keep the highest-valued cause-carrying samples, ring bounded at
        ``ex_cap`` — replace the current minimum when the ring is full, so
        a burst of a million tail samples retains exactly ``ex_cap``."""
        ex = self.exemplars
        self.ex_recorded += 1
        if len(ex) < self.ex_cap:
            ex.append([v, str(cause), time.time()])
            return
        self.ex_evicted += 1
        mi = 0
        for i in range(1, len(ex)):
            if ex[i][0] < ex[mi][0]:
                mi = i
        if v >= ex[mi][0]:
            ex[mi] = [v, str(cause), time.time()]

    @staticmethod
    def _percentile_from(buckets, edges, count, observed_max, q: float) -> Optional[float]:
        if count == 0:
            return None
        target = count * q / 100.0
        cum = 0
        for i, n in enumerate(buckets):
            if n == 0:
                continue
            prev_cum = cum
            cum += n
            if cum >= target:
                if i < len(edges):
                    upper = edges[i]
                    lower = edges[i - 1] if i > 0 else 0.0
                else:  # overflow bucket: bounded by the observed max
                    lower = edges[-1]
                    upper = max(observed_max, lower)
                frac = (target - prev_cum) / n
                return lower + (upper - lower) * frac
        return observed_max if observed_max > -math.inf else None

    def percentile(self, q: float) -> Optional[float]:
        """Estimate the q-th percentile (0-100) from the bucket counts."""
        return self._percentile_from(self.buckets, self.edges, self.count, self.max, q)

    def checkpoint(self) -> tuple:
        """Opaque marker for :meth:`since` — snapshot-and-diff lets a
        harness report THIS phase's distribution out of a histogram other
        phases also record into (perf/fanout_path.py separates its A/B
        modes this way)."""
        return (list(self.buckets), self.count, self.sum)

    def since(self, checkpoint: tuple) -> dict:
        """Snapshot of ONLY the samples recorded after ``checkpoint``
        (same shape as :meth:`snapshot`, minus min/max — those are not
        recoverable from a bucket diff)."""
        prev_buckets, prev_count, prev_sum = checkpoint
        buckets = [a - b for a, b in zip(self.buckets, prev_buckets)]
        count = self.count - prev_count
        p50 = self._percentile_from(buckets, self.edges, count, self.max, 50)
        p99 = self._percentile_from(buckets, self.edges, count, self.max, 99)
        return {
            "count": count,
            "sum": round(self.sum - prev_sum, 4),
            "p50": round(p50, 4) if p50 is not None else None,
            "p99": round(p99, 4) if p99 is not None else None,
            "unit": self.unit,
        }

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "sum": round(self.sum, 4),
            "min": round(self.min, 4) if self.count else None,
            "max": round(self.max, 4) if self.count else None,
            "p50": round(self.percentile(50), 4) if self.count else None,
            "p99": round(self.percentile(99), 4) if self.count else None,
            "unit": self.unit,
            # sparse bucket map: upper-edge -> count (readable + bounded)
            "buckets": {
                ("+inf" if i == len(self.edges) else repr(self.edges[i])): n
                for i, n in enumerate(self.buckets)
                if n
            },
        }
        if self.exemplars:
            # highest first: the tail's provenance, cause id attached
            out["exemplars"] = [
                [round(v, 4), cause, round(ts, 3)]
                for v, cause, ts in sorted(self.exemplars, reverse=True)
            ]
        return out


#: collector: fn(owner) -> {metric_name: numeric value}; gauge semantics,
#: summed across collectors that report the same name
MetricCollector = Callable[[Any], Dict[str, float]]


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._collectors: List[Tuple["weakref.ref", MetricCollector]] = []
        #: per-name collector aggregation: "sum" (default — counter-like
        #: totals over hubs/peers) or "max" (non-additive gauges: ages,
        #: lags — two hubs each 5 ms behind are 5 ms behind, not 10)
        self._agg: Dict[str, str] = {}

    # ------------------------------------------------------------------ get-or-create
    def _get(self, name: str, cls, **kwargs):
        name = _sanitize(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} is a {type(m).__name__}, not a {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get(name, Gauge, help=help)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "", unit: str = "ms",
                  lo: float = 0.001, hi: float = 120_000.0) -> Histogram:
        return self._get(name, Histogram, help=help, unit=unit, lo=lo, hi=hi)

    def find(self, name: str):
        """The metric if it exists — never creates (report paths must not
        mint empty metrics just by looking)."""
        return self._metrics.get(_sanitize(name))

    # ------------------------------------------------------------------ collectors
    def register_collector(self, owner: Any, fn: MetricCollector) -> None:
        """Attach a pull-time collector. ``owner`` is weakly referenced:
        when it dies the collector drops out at the next collection — no
        dispose() protocol needed, no pinning."""
        with self._lock:
            self._collectors.append((weakref.ref(owner), fn))

    def unregister_collector(self, owner: Any) -> None:
        with self._lock:
            self._collectors = [
                (ref, fn) for ref, fn in self._collectors if ref() is not owner
            ]

    def set_aggregation(self, name: str, mode: str) -> None:
        """Declare how collector values for ``name`` combine across owners:
        ``"sum"`` (default) or ``"max"``. Non-additive gauges (ages, lags)
        MUST declare max, or a process with N hubs scrapes N× the truth."""
        if mode not in ("sum", "max"):
            raise ValueError(f"unknown aggregation {mode!r}")
        with self._lock:
            self._agg[_sanitize(name)] = mode

    def _collect(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        dead = False
        with self._lock:
            collectors = list(self._collectors)
            agg = dict(self._agg)
        for ref, fn in collectors:
            owner = ref()
            if owner is None:
                dead = True
                continue
            try:
                values = fn(owner)
            except Exception:  # noqa: BLE001 — one broken collector never kills a scrape
                continue
            for k, v in values.items():
                if isinstance(v, (int, float)):
                    k = _sanitize(k)
                    if agg.get(k) == "max":
                        totals[k] = max(totals.get(k, v), v)
                    else:
                        totals[k] = totals.get(k, 0) + v
        if dead:
            with self._lock:
                self._collectors = [(r, f) for r, f in self._collectors if r() is not None]
        return totals

    # ------------------------------------------------------------------ export
    def _exemplar_totals(self) -> Dict[str, float]:
        """Registry-wide exemplar accounting (ISSUE 19): summed across all
        histograms, emitted only once any exemplar exists — a repo that
        never passes ``cause=`` scrapes exactly what it did before."""
        rec = ev = 0
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                rec += m.ex_recorded
                ev += m.ex_evicted
        if rec == 0:
            return {}
        return {
            "fusion_exemplars_recorded_total": float(rec),
            "fusion_exemplars_evicted_total": float(ev),
        }

    def snapshot(self) -> dict:
        """Nested dict of everything: registered metrics + collector sums."""
        out: Dict[str, Any] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            out[m.name] = m.snapshot()
        for k, v in self._collect().items():
            if k not in out:  # registered metrics win over collector shadows
                out[k] = v
        for k, v in self._exemplar_totals().items():
            out.setdefault(k, v)
        return out

    def flat_samples(self) -> Dict[str, float]:
        """One flat ``{series: value}`` map — the transport shape of a mesh
        telemetry snapshot (ISSUE 18). Counters/gauges contribute their
        value; histograms contribute ``_sum``/``_count`` (their buckets are
        per-process detail the fleet merge has no honest semantics for);
        collector samples ride as-is, registered metrics winning shadows."""
        out: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                out[f"{m.name}_sum"] = float(m.sum)
                out[f"{m.name}_count"] = float(m.count)
            else:
                out[m.name] = float(m.value)
        for k, v in self._collect().items():
            if k not in out:
                out[k] = float(v)
        for k, v in self._exemplar_totals().items():
            out.setdefault(k, v)
        return out

    def max_aggregated_names(self) -> List[str]:
        """The declared-MAX series names — shipped with every mesh snapshot
        so the cross-host merge applies the same non-additive contract the
        in-process collector merge does."""
        with self._lock:
            return sorted(k for k, mode in self._agg.items() if mode == "max")

    def render_prometheus(self) -> str:
        """Prometheus text exposition (v0.0.4)."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                cum = 0
                for i, n in enumerate(m.buckets):
                    cum += n
                    le = "+Inf" if i == len(m.edges) else repr(m.edges[i])
                    lines.append(f'{m.name}_bucket{{le="{le}"}} {cum}')
                lines.append(f"{m.name}_sum {m.sum}")
                lines.append(f"{m.name}_count {m.count}")
            else:
                v = m.value
                lines.append(f"{m.name} {v}")
        collected = self._collect()
        typed = {m.name for m in metrics}
        for k in sorted(collected):
            # labeled samples (fusion_routed_calls_total{peer="m0"}) belong
            # to their base family: ONE valid "# TYPE <base> gauge" line,
            # never a TYPE line with a brace-suffixed name (which breaks
            # the whole scrape — the exposition name charset is strict)
            base = k.partition("{")[0]
            if k == base and base in typed:
                continue  # registered metrics win over collector shadows
            if base not in typed:
                lines.append(f"# TYPE {base} gauge")
                typed.add(base)
            lines.append(f"{k} {collected[k]}")
        for k, v in sorted(self._exemplar_totals().items()):
            if k not in typed:
                lines.append(f"# TYPE {k} gauge")
                lines.append(f"{k} {v}")
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        """Drop every metric, collector and aggregation override (tests)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()
            self._agg.clear()


_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    """The process-wide registry — components report here with no wiring,
    exactly like ``resilience.events.global_events()``."""
    return _GLOBAL


# ---------------------------------------------------------------------- waves

_wave_seq = itertools.count(1)


def next_wave_seq() -> int:
    """Mint the next process-wide wave sequence number. The backend mints
    it at ``_begin_wave`` (so the flight recorder can stamp events DURING
    wave application with the wave they belong to) and hands it back to
    :meth:`WaveProfiler.record_wave` — one numbering for both rings."""
    return next(_wave_seq)


class WaveProfiler:
    """Per-wave timeline ring buffer for a TpuGraphBackend.

    One record per device wave dispatch (union / lanes / seq / collect /
    icasc): seed count, newly-invalidated size, device milliseconds
    (dispatch → readback), host-apply milliseconds (two-tier apply + hook
    drain), the journal depth the preceding flush replayed (pre/post
    coalescing) and its host cost, and the wave's cause id — the same id
    the fan-out stamps into ``$sys-c`` frames, so a client-side delivery
    sample joins back to its wave record.

    Bounded and cheap: a deque of small dicts plus two registry histograms;
    ``enabled = False`` reduces every call to one attribute check (the
    <3% live-path overhead budget is enforced by bench telemetry)."""

    def __init__(self, capacity: int = 256, metrics: Optional[MetricsRegistry] = None):
        self.enabled = True
        self._ring: Deque[dict] = deque(maxlen=capacity)
        self.metrics = metrics if metrics is not None else global_metrics()
        self.waves_recorded = 0
        self.flushes_recorded = 0
        # totals survive ring eviction — the summary stays whole-run honest
        self.device_ms_total = 0.0
        self.apply_ms_total = 0.0
        self.flush_ms_total = 0.0
        self.newly_total = 0
        self._pending_flush: Optional[dict] = None
        #: fused-chain accounting (ISSUE 7): logical waves per physical
        #: dispatch, and the bench-layer negative-timing rejects that were
        #: previously counted only inside BENCH_*.json
        self.fused_dispatches = 0
        self.fused_waves_total = 0
        self.timing_rejects_total = 0

    # ------------------------------------------------------------------ feed
    def note_flush(self, journal_pre: int, journal_post: int, host_ms: float) -> None:
        """Record one journal flush; attached to the NEXT wave record (the
        flush a wave path runs before dispatching is part of that wave's
        latency story). A flush with no following wave stays visible in
        the totals."""
        if not self.enabled:
            return
        self.flushes_recorded += 1
        self.flush_ms_total += host_ms
        self._pending_flush = {
            "journal_pre": journal_pre,
            "journal_post": journal_post,
            "flush_ms": round(host_ms, 3),
        }

    def note_fused_dispatch(self, fused_depth: int) -> None:
        """One physical device dispatch that FUSED ``fused_depth`` > 1
        logical waves (ISSUE 7 wave-chain fusion). Feeds the
        ``fusion_wave_fused_depth`` histogram — the CI live smoke asserts
        it is non-empty with p50 > 1, i.e. the fused path actually engaged
        instead of silently falling back to eager one-wave dispatches.
        Plain one-wave dispatches are NOT recorded: a workload full of
        ordinary lone bursts would otherwise dilute the engagement metric
        below the gate even while every chain-eligible wave fused (and the
        log-bucket interpolation of depth-1 samples reads below 1.0)."""
        if not self.enabled or fused_depth <= 1:
            return
        self.fused_dispatches += 1
        self.fused_waves_total += int(fused_depth)
        self.metrics.histogram(
            "fusion_wave_fused_depth",
            help="logical waves per physical device dispatch (wave-chain fusion; depth>1 only)",
            unit="waves", lo=1.0, hi=4096.0,
        ).record(float(fused_depth))

    def note_timing_rejects(self, n: int, source: str = "") -> None:
        """Negative chain-difference samples rejected by the PR-6 timing
        belt (bench.py / live_path.py) — previously bench-local counters;
        exported here as ``fusion_wave_timing_rejects_total`` and surfaced
        in ``FusionMonitor.report()["waves"]`` so the belt is observable
        in production scrapes, not just BENCH_*.json."""
        if n <= 0:
            return
        self.timing_rejects_total += int(n)
        c = self.metrics.counter(
            "fusion_wave_timing_rejects_total",
            help="negative per-wave timing samples rejected as measurement artifacts",
        )
        c.inc(int(n))

    def record_wave(
        self,
        kind: str,
        seeds: int,
        newly: int,
        device_ms: float,
        apply_ms: float,
        cause: Optional[str] = None,
        groups: Optional[int] = None,
        seq: Optional[int] = None,
        fused_depth: Optional[int] = None,
        seq_span: Optional[tuple] = None,
        dispatches: Optional[int] = None,
        mesh: Optional[dict] = None,
    ) -> None:
        if not self.enabled:
            return
        rec = {
            "seq": seq if seq is not None else next(_wave_seq),
            "kind": kind,
            "at": time.time(),
            "seeds": int(seeds),
            "newly": int(newly),
            "device_ms": round(device_ms, 3),
            "apply_ms": round(apply_ms, 3),
            "cause": cause,
        }
        if groups is not None:
            rec["groups"] = int(groups)
        if fused_depth is not None:
            # per-logical-wave identity survives physical fusion: the
            # record covers the CONTIGUOUS seq span [seq_span[0],
            # seq_span[1]] (one seq per logical wave), and explain()
            # resolves any seq inside the span to this record
            rec["fused_depth"] = int(fused_depth)
        if seq_span is not None:
            rec["seq_span"] = [int(seq_span[0]), int(seq_span[1])]
        if dispatches is not None:
            rec["dispatches"] = int(dispatches)
        if mesh is not None:
            # the shard hop: exchange mode, collective levels, placement
            # epoch — explain() renders it ("frontier exchanged on-mesh")
            rec["mesh"] = dict(mesh)
        if self._pending_flush is not None:
            rec.update(self._pending_flush)
            self._pending_flush = None
        self._ring.append(rec)
        self.waves_recorded += 1
        self.device_ms_total += device_ms
        self.apply_ms_total += apply_ms
        self.newly_total += int(newly)
        self.metrics.histogram(
            "fusion_wave_device_ms", help="device wave dispatch->readback latency"
        ).record(device_ms, cause=cause)
        self.metrics.histogram(
            "fusion_wave_apply_ms", help="host two-tier wave application latency"
        ).record(apply_ms, cause=cause)

    # ------------------------------------------------------------------ query
    def recent(self, n: Optional[int] = None) -> List[dict]:
        out = list(self._ring)
        return out[-n:] if n is not None else out

    def summary(self) -> dict:
        dev = self.metrics.find("fusion_wave_device_ms")
        fused = self.metrics.find("fusion_wave_fused_depth")
        return {
            "enabled": self.enabled,
            "waves_recorded": self.waves_recorded,
            "flushes_recorded": self.flushes_recorded,
            "newly_total": self.newly_total,
            "device_ms_total": round(self.device_ms_total, 2),
            "apply_ms_total": round(self.apply_ms_total, 2),
            "flush_ms_total": round(self.flush_ms_total, 2),
            "device_ms_p50": (
                round(dev.percentile(50), 4) if dev is not None and dev.count else None
            ),
            "device_ms_p99": (
                round(dev.percentile(99), 4) if dev is not None and dev.count else None
            ),
            # fused-chain engagement (ISSUE 7): dispatches carrying >1
            # logical wave; the live smoke asserts fused_depth_p50 > 1
            "fused_dispatches": self.fused_dispatches,
            "fused_waves_total": self.fused_waves_total,
            "fused_depth_p50": (
                round(fused.percentile(50), 2)
                if fused is not None and fused.count else None
            ),
            "fused_depth_p99": (
                round(fused.percentile(99), 2)
                if fused is not None and fused.count else None
            ),
            # the PR-6 negative-timing belt, observable (ISSUE 7 satellite)
            "timing_rejects": self.timing_rejects_total,
        }

    def report(self, recent: int = 32) -> dict:
        return {**self.summary(), "recent": self.recent(recent)}
