"""ConsistencyAuditor — the online correctness sentinel (ISSUE 4 tentpole).

``validate_hub``/``validate_mirror`` existed since the invariants PR but
had zero callers outside tests — the correctness story never RAN on a live
process. This auditor closes that gap: a background task that, each cycle,

1. runs a **sampled** ``validate_hub`` sweep (I1-I5 structural invariants
   over a random fraction of the registry — the full sweep amortizes over
   cycles instead of stalling a live loop on one O(graph) pass);
2. cross-checks the device CSR mirror against host truth
   (``validate_mirror``, M1-M2) when a graph backend is attached;
3. probes a **canary key**: a private compute method is invalidated and
   re-read through the full invalidate→recompute machinery; the observed
   freshness latency records into ``fusion_canary_staleness_ms`` and a
   stale read-back (the value did not advance) is itself a violation —
   the sentinel that catches "invalidation stopped propagating" even when
   the structure still validates.

Violations export as the ``fusion_invariant_violations`` counter, trip a
``ResilienceEvents`` ledger event (so breaker dashboards see correctness
degradation next to connectivity degradation) and land in the flight
recorder — ``explain``/``/trace`` show them in context.

Surfaced via ``FusionMonitor.report()["audit"]`` and started with
``monitor.start_auditor()`` beside ``start_reporter()``.

Imports from ``core`` are lazy (``diagnostics`` is imported by
``core.computed`` at module scope — this module must not close the cycle).
"""
from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Optional

from .flight_recorder import RECORDER
from .invariants import validate_hub, validate_mirror
from .metrics import global_metrics

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["ConsistencyAuditor"]


def _make_canary(hub):
    """A private single-key compute service — the staleness sentinel rides
    the REAL invalidate/recompute machinery, not a synthetic timer."""
    from ..core.service import ComputeService, compute_method

    class _CanaryService(ComputeService):
        def __init__(self, h):
            super().__init__(h)
            self.value = 0

        @compute_method
        async def canary(self) -> int:
            return self.value

    return _CanaryService(hub)


class ConsistencyAuditor:
    def __init__(
        self,
        hub,
        backend=None,
        period: float = 30.0,
        sample: float = 0.25,
        canary: bool = True,
        metrics=None,
        events=None,
        recorder=None,
        seed: Optional[int] = None,
    ):
        self.hub = hub
        #: TpuGraphBackend whose mirror each cycle cross-checks; defaults
        #: to the hub's attached backend (None skips the mirror sweep)
        self.backend = backend if backend is not None else hub.graph_backend
        self.period = period
        self.sample = sample
        self.canary_enabled = canary
        self.metrics = metrics if metrics is not None else global_metrics()
        if events is None:
            from ..resilience.events import global_events

            events = global_events()
        self.events = events
        self.recorder = recorder if recorder is not None else RECORDER
        self._rng = random.Random(seed)
        self._canary_svc = None
        self._task: Optional[asyncio.Task] = None
        self._disposed = False
        # -- counters (collector-fed; weak-registered like every component)
        self.sweeps = 0
        self.violations_total = 0
        self.canary_probes = 0
        self.canary_failures = 0
        self.last_report: Optional[dict] = None
        self.metrics.register_collector(self, ConsistencyAuditor._collect_metrics)

    def _collect_metrics(self) -> dict:
        return {
            "fusion_invariant_violations": self.violations_total,
            "fusion_audit_sweeps_total": self.sweeps,
            "fusion_canary_probes_total": self.canary_probes,
            "fusion_canary_failures_total": self.canary_failures,
        }

    # ------------------------------------------------------------------ cycle
    async def audit_once(self) -> dict:
        """One audit cycle. Returns (and retains as ``last_report``) a
        JSON-safe dict; violations are counted, ledgered and journaled."""
        t0 = time.perf_counter()
        hub_report = validate_hub(self.hub, sample=self.sample, rng=self._rng)
        mirror_report = None
        if self.backend is not None:
            mirror_report = validate_mirror(
                self.backend, sample=self.sample, rng=self._rng
            )
        canary_ms = None
        canary_ok = True
        if self.canary_enabled:
            canary_ms, canary_ok = await self._canary_probe()

        violations = list(hub_report.violations)
        if mirror_report is not None:
            violations.extend(mirror_report.violations)
        if not canary_ok:
            violations.append("C1: canary key served a stale value after invalidation")
        if violations:
            self.violations_total += len(violations)
            self.events.record(
                "invariant_violation",
                f"{len(violations)} violation(s), first: {violations[0]}",
            )
            if self.recorder.enabled:
                self.recorder.note(
                    "invariant_violation",
                    key="auditor",
                    detail=violations[0],
                )
            log.warning("auditor found %d invariant violation(s): %s",
                        len(violations), violations[0])
        self.sweeps += 1
        self.last_report = {
            "at": time.time(),
            "sweeps": self.sweeps,
            "sample": self.sample,
            "checked_nodes": hub_report.checked_nodes
            + (mirror_report.checked_nodes if mirror_report is not None else 0),
            "checked_edges": hub_report.checked_edges,
            "violations": violations[:20],
            "violations_total": self.violations_total,
            "canary_staleness_ms": canary_ms,
            "canary_ok": canary_ok,
            "audit_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        return self.last_report

    async def _canary_probe(self) -> tuple:
        """Invalidate + re-read the canary through the real machinery;
        the invalidate→fresh-read latency is the staleness sample."""
        from ..core.context import invalidating

        if self._canary_svc is None:
            self._canary_svc = _make_canary(self.hub)
        svc = self._canary_svc
        svc.value += 1
        want = svc.value
        t0 = time.perf_counter()
        with invalidating():
            await svc.canary()
        got = await svc.canary()
        ms = (time.perf_counter() - t0) * 1e3
        self.canary_probes += 1
        ok = got == want
        if not ok:
            self.canary_failures += 1
        self.metrics.histogram(
            "fusion_canary_staleness_ms",
            help="auditor canary: invalidation -> fresh recompute observed",
        ).record(ms)
        return round(ms, 4), ok

    # ------------------------------------------------------------------ lifecycle
    def start(self, period: Optional[float] = None) -> asyncio.Task:
        """Run :meth:`audit_once` every ``period`` seconds from a background
        task. Idempotent while running; stopped by :meth:`dispose`."""
        if self._disposed:
            raise RuntimeError("auditor is disposed")
        if period is not None:
            # applied BEFORE the running-task early return: restarting with
            # a new period must retime the live loop (it re-reads
            # self.period each cycle), not be silently dropped
            self.period = period
        if self._task is not None and not self._task.done():
            return self._task

        async def _loop() -> None:
            # first sweep IMMEDIATELY: an operator starting the auditor
            # mid-incident must get an "audit" section on the first scrape,
            # not after a full period of silence
            while True:
                try:
                    await self.audit_once()
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — the sentinel must outlive one bad sweep
                    log.exception("auditor cycle failed")
                await asyncio.sleep(self.period)

        self._task = asyncio.get_event_loop().create_task(_loop())
        return self._task

    def dispose(self) -> None:
        """Stop the loop and detach the metrics collector (idempotent)."""
        if self._disposed:
            return
        self._disposed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.metrics.unregister_collector(self)
