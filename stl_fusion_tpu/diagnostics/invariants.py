"""Explicit graph-invariant checks — the build's race-detection story.

The reference has no sanitizer; its correctness rests on a locking
discipline (per-node monitor, per-input single-flight locks, the
double-checked Read→Lock→RetryRead pattern) plus scattered debug assertions
(SURVEY §5.2). This build makes the discipline *checkable*: ``validate_hub``
sweeps the registry and verifies the structural invariants that the locking
is supposed to preserve, and ``validate_mirror`` cross-checks the device CSR
mirror against host truth. Tests and stress suites call these after
hammering the graph; long-running hosts can sample them periodically (they
only take the per-node locks briefly, never the compute locks).

Invariants checked (references are the reference's enforcement points):
- I1  state/output coherence: CONSISTENT ⇒ output set; COMPUTING ⇒ no
      output (TrySetOutput, Computed.cs:141-160).
- I2  edge symmetry: for every consistent dependent d and u in d.used,
      (d.input, d.version) ∈ u.used_by — the AddUsed/AddUsedBy pairing
      (Computed.cs:347-377).
- I3  no forward edges from invalidated nodes: an INVALIDATED node's used
      set is empty (invalidation clears edges, Computed.cs:204-217).
- I4  registry interning: every registry entry resolves to a computed whose
      input equals its key (ComputedRegistry.Register, :72-105).
- I5  stale used_by entries must be version-mismatched: a used_by entry
      whose (input, version) resolves to a LIVE CONSISTENT computed of the
      SAME version must be a real dependent edge (otherwise an invalidation
      would be lost — the wave-correctness invariant).
- M1  mirror epoch coherence: device node_epoch == host mirror bookkeeping
      for every mapped node (after flush).
- M2  mirror invalidation superset: an invalidated host node that is mapped
      is marked invalid on device OR has a pending journal entry.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from ..core.hub import FusionHub
    from ..graph.backend import TpuGraphBackend

__all__ = ["InvariantViolation", "InvariantReport", "validate_hub", "validate_mirror"]


def _sample_items(items: list, sample: float, rng) -> list:
    """``rng.sample`` selection of a ``sample`` fraction — O(selected)
    picks, not an O(n) per-item coin-flip pass (the auditor runs this on
    the event loop every cycle)."""
    if sample >= 1.0 or not items:
        return items
    import random

    rng = rng if rng is not None else random.Random()
    k = max(int(len(items) * sample), 1)
    return rng.sample(items, k) if k < len(items) else items


class InvariantViolation(AssertionError):
    """Raised by ``*.require()`` when a sweep found violations."""


@dataclass
class InvariantReport:
    checked_nodes: int = 0
    checked_edges: int = 0
    violations: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def require(self) -> "InvariantReport":
        if self.violations:
            head = "\n  ".join(self.violations[:20])
            more = f"\n  … +{len(self.violations) - 20} more" if len(self.violations) > 20 else ""
            raise InvariantViolation(
                f"{len(self.violations)} graph invariant violation(s):\n  {head}{more}"
            )
        return self

    def merge(self, other: "InvariantReport") -> "InvariantReport":
        self.checked_nodes += other.checked_nodes
        self.checked_edges += other.checked_edges
        self.violations.extend(other.violations)
        return self


def validate_hub(
    hub: "FusionHub", sample: float = 1.0, rng=None
) -> InvariantReport:
    """Sweep the registry and check I1-I5. Safe to run concurrently with
    reads/invalidations — it tolerates in-flight transitions by re-reading
    node state around each check (a node may legally change state mid-sweep;
    only *stable* contradictions are reported).

    ``sample < 1.0`` checks a random fraction of nodes — the ONLINE shape
    (diagnostics.auditor): a live process amortizes the full sweep over
    cycles instead of stalling its loop on one O(graph) pass. Edge checks
    still follow every edge of a sampled node, so a violation anywhere is
    eventually found with probability → 1 over cycles. Selection is
    ``rng.sample`` (O(selected)), never a per-item coin flip — the
    remaining O(n) is the C-level snapshot of the map, the irreducible
    cost of a consistent view."""
    from ..core.consistency import ConsistencyState  # local: avoid cycle

    report = InvariantReport()
    registry = hub.registry
    with registry._lock:
        items = list(registry._map.items())
    items = _sample_items(items, sample, rng)

    for input, ref in items:
        c = ref()
        if c is None:
            continue  # dead entry; weakref callback will reap it
        report.checked_nodes += 1

        # I4: interning coherence
        if c.input != input:
            report.violations.append(f"I4: registry key {input!r} maps to node of {c.input!r}")

        state = c._state
        out = c._output
        # I1: state/output coherence (re-read state to tolerate races)
        if state == ConsistencyState.CONSISTENT and out is None and c._state == state:
            report.violations.append(f"I1: {c!r} CONSISTENT without output")
        if state == ConsistencyState.COMPUTING and out is not None and c._state == state:
            report.violations.append(f"I1: {c!r} COMPUTING but has output")

        with c._lock:
            used = list(c._used)
            state_now = c._state
        if state_now == ConsistencyState.INVALIDATED:
            # I3: invalidation clears forward edges
            if used:
                report.violations.append(f"I3: invalidated {c!r} still lists {len(used)} deps")
            continue
        # I2: edge symmetry for live dependents
        for u in used:
            report.checked_edges += 1
            with u._lock:
                has_back = (c.input, c.version) in u._used_by
                u_state = u._state
            if not has_back and c._state != ConsistencyState.INVALIDATED:
                if u_state != ConsistencyState.INVALIDATED:
                    report.violations.append(
                        f"I2: {c!r} uses {u!r} but has no used_by back-edge"
                    )

        # I5: used_by entries that resolve to a live same-version node must
        # be real dependents (else a cascade would skip them)
        with c._lock:
            back_edges = list(c._used_by)
        for (dep_input, dep_version) in back_edges:
            d = registry.get(dep_input)
            if d is None or d.version != dep_version:
                continue  # stale entry — legal, pruner's job
            if d.is_invalidated:
                continue
            with d._lock:
                forward = c in d._used
            if not forward and not d.is_invalidated and not c.is_invalidated:
                report.violations.append(
                    f"I5: {c!r} lists dependent {d!r} which does not use it"
                )
    return report


def validate_mirror(
    backend: "TpuGraphBackend", sample: float = 1.0, rng=None
) -> InvariantReport:
    """Flush pending events, then check M1-M2 device↔host coherence.

    ``sample < 1.0`` checks a random fraction of mapped nodes (the online
    auditor shape — a live 10M-node mirror must not stall the event loop
    on one O(n) Python pass; selection is O(selected) via ``rng.sample``);
    the flush itself is cheap when the journal is empty."""
    import numpy as np

    report = InvariantReport()
    backend.flush()
    graph = backend.graph
    invalid = graph.invalid_mask()
    with backend._lock:
        items = list(backend._id_by_input.items())
    items = _sample_items(items, sample, rng)
    for input, nid in items:
        ref = backend._computed_by_id.get(nid)
        c = ref() if ref is not None else None
        if c is None:
            continue
        report.checked_nodes += 1
        if nid >= graph.n_nodes:
            report.violations.append(f"M1: node id {nid} out of range for {input!r}")
            continue
        if c.is_invalidated and not bool(invalid[nid]) and not backend._journal:
            report.violations.append(
                f"M2: host-invalidated {input!r} (nid {nid}) not invalid on device"
            )
    return report
