"""Activity-style tracing (SURVEY §5.1; src/Stl/Diagnostics/).

The reference hangs a ``System.Diagnostics.ActivitySource`` off every
component (registry prune spans, op-log reader reads, invalidation replays,
RPC inbound calls). Here a module-level ``ActivitySource`` registry produces
``Span`` context managers that record (name, tags, duration, error) into a
bounded in-process buffer and notify listeners; exporters (logging, test
assertions) subscribe via ``add_listener``.

Spans nest via a contextvar, so a trace tree can be reconstructed from
``parent_id`` — the analogue of Activity.Current parenting.
"""
from __future__ import annotations

import contextvars
import itertools
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

log = logging.getLogger("stl_fusion_tpu.tracing")

__all__ = [
    "Span",
    "ActivitySource",
    "get_activity_source",
    "add_listener",
    "remove_listener",
    "recent_spans",
    "clear_recent",
    "span_cause_id",
    "current_cause_id",
    "find_span_by_cause",
]

#: process-unique cause-id prefix (shared with graph/backend.py wave ids):
#: two hosts minting "wave#1" must not collide when their frames meet in
#: one client's telemetry. pid ALONE is not unique across hosts — two
#: containers both running as pid 1 would mint byte-identical ids — so a
#: random suffix minted once at import disambiguates them (4 bytes: a
#: 2-byte suffix birthday-collides past ~300 same-pid containers)
CAUSE_PREFIX = f"{os.getpid():x}-{int.from_bytes(os.urandom(4), 'big'):08x}"

_span_ids = itertools.count(1)
_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "fusion_current_span", default=None
)
_listeners: List[Callable[["Span"], None]] = []
_recent: Deque["Span"] = deque(maxlen=2048)
_sources: Dict[str, "ActivitySource"] = {}


@dataclass
class Span:
    source: str
    name: str
    tags: Dict[str, Any] = field(default_factory=dict)
    span_id: int = 0
    parent_id: Optional[int] = None
    started_at: float = 0.0
    duration: Optional[float] = None
    # error is recorded as (type name, message) — keeping the live exception
    # here would pin its traceback frames in the span buffer
    error_type: Optional[str] = None
    error_message: Optional[str] = None
    _token: Any = None

    @property
    def failed(self) -> bool:
        return self.error_type is not None

    def set_tag(self, key: str, value: Any) -> "Span":
        self.tags[key] = value
        return self

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe span view (the ``/trace`` gateway route ships these)."""
        return {
            "source": self.source,
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "duration_ms": (
                round(self.duration * 1e3, 4) if self.duration is not None else None
            ),
            "error_type": self.error_type,
            "error_message": self.error_message,
            "tags": {k: repr(v) if not isinstance(v, (int, float, str, bool, type(None))) else v
                     for k, v in self.tags.items()},
        }

    def __enter__(self) -> "Span":
        self.span_id = next(_span_ids)
        parent = _current_span.get()
        self.parent_id = parent.span_id if parent is not None else None
        self.started_at = time.perf_counter()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration = time.perf_counter() - self.started_at
        if exc is not None:
            self.error_type = type(exc).__name__
            self.error_message = str(exc)
        _current_span.reset(self._token)
        _recent.append(self)
        for listener in list(_listeners):
            try:
                listener(self)
            except Exception:  # noqa: BLE001 — listeners never break traced code
                log.exception("trace listener failed")


class ActivitySource:
    def __init__(self, name: str):
        self.name = name

    def span(self, name: str, **tags: Any) -> Span:
        return Span(self.name, name, tags)


def get_activity_source(name: str) -> ActivitySource:
    source = _sources.get(name)
    if source is None:
        source = _sources[name] = ActivitySource(name)
    return source


def current_span() -> Optional[Span]:
    return _current_span.get()


def span_cause_id(span: Span) -> str:
    """The canonical cause-id form of a span — the SAME format
    ``TpuGraphBackend._begin_wave`` stamps into ``$sys-c`` frames, so a
    host-led invalidation under an open span joins the trace machinery
    exactly like a device wave does."""
    return f"{CAUSE_PREFIX}/{span.source}:{span.name}#{span.span_id}"


def current_cause_id() -> Optional[str]:
    """Cause id of the currently open span, or None outside any span."""
    span = _current_span.get()
    return span_cause_id(span) if span is not None else None


def wave_shaped_cause(seq: int) -> str:
    """A wave-shaped cause id (``<prefix>/wave#<seq>``) for wave work no
    backend span began — the routed graph driven directly by a perf
    worker still keys its mesh trace segments in the ONE cause-id format
    (ISSUE 18), so stitch/explain join them like any backend wave."""
    return f"{CAUSE_PREFIX}/wave#{seq}"


def find_span_by_cause(cause: str) -> Optional[Span]:
    """Resolve a span-shaped cause id back to its recorded span (None for
    wave-shaped causes, foreign-process causes, or evicted spans)."""
    prefix, sep, rest = cause.partition("/")
    if not sep or prefix != CAUSE_PREFIX or "#" not in rest:
        return None
    name_part, _, id_part = rest.rpartition("#")
    if ":" not in name_part:
        # wave-shaped rest ("wave#N"): span-shaped causes are always
        # "<source>:<name>#<id>" — without the colon this would parse N as
        # a span id and resolve to an unrelated span
        return None
    try:
        span_id = int(id_part)
    except ValueError:
        return None
    # snapshot (one C-level copy) before iterating: a worker thread closing
    # a span appends to _recent, and a bare Python-level iteration racing
    # that append raises "deque mutated during iteration" mid-explain()
    for s in reversed(list(_recent)):
        if s.span_id == span_id:
            return s
    return None


def add_listener(listener: Callable[[Span], None]) -> None:
    _listeners.append(listener)


def remove_listener(listener: Callable[[Span], None]) -> None:
    if listener in _listeners:
        _listeners.remove(listener)


def recent_spans(source: Optional[str] = None, name: Optional[str] = None) -> List[Span]:
    return [
        s
        for s in list(_recent)  # snapshot: appends from other threads race
        if (source is None or s.source == source) and (name is None or s.name == name)
    ]


def clear_recent() -> None:
    """Drop the recorded span buffer. The buffer (and the listener list)
    are module-level state that would otherwise LEAK across tests — a span
    recorded by one test shows up in the next test's ``recent_spans()``.
    ``tests/conftest.py`` calls this per test (and snapshots/restores the
    listener list) so span assertions are hermetic."""
    _recent.clear()
