"""Activity-style tracing (SURVEY §5.1; src/Stl/Diagnostics/).

The reference hangs a ``System.Diagnostics.ActivitySource`` off every
component (registry prune spans, op-log reader reads, invalidation replays,
RPC inbound calls). Here a module-level ``ActivitySource`` registry produces
``Span`` context managers that record (name, tags, duration, error) into a
bounded in-process buffer and notify listeners; exporters (logging, test
assertions) subscribe via ``add_listener``.

Spans nest via a contextvar, so a trace tree can be reconstructed from
``parent_id`` — the analogue of Activity.Current parenting.
"""
from __future__ import annotations

import contextvars
import itertools
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

log = logging.getLogger("stl_fusion_tpu.tracing")

__all__ = [
    "Span",
    "ActivitySource",
    "get_activity_source",
    "add_listener",
    "remove_listener",
    "recent_spans",
    "clear_recent",
]

_span_ids = itertools.count(1)
_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "fusion_current_span", default=None
)
_listeners: List[Callable[["Span"], None]] = []
_recent: Deque["Span"] = deque(maxlen=2048)
_sources: Dict[str, "ActivitySource"] = {}


@dataclass
class Span:
    source: str
    name: str
    tags: Dict[str, Any] = field(default_factory=dict)
    span_id: int = 0
    parent_id: Optional[int] = None
    started_at: float = 0.0
    duration: Optional[float] = None
    # error is recorded as (type name, message) — keeping the live exception
    # here would pin its traceback frames in the span buffer
    error_type: Optional[str] = None
    error_message: Optional[str] = None
    _token: Any = None

    @property
    def failed(self) -> bool:
        return self.error_type is not None

    def set_tag(self, key: str, value: Any) -> "Span":
        self.tags[key] = value
        return self

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe span view (the ``/trace`` gateway route ships these)."""
        return {
            "source": self.source,
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "duration_ms": (
                round(self.duration * 1e3, 4) if self.duration is not None else None
            ),
            "error_type": self.error_type,
            "error_message": self.error_message,
            "tags": {k: repr(v) if not isinstance(v, (int, float, str, bool, type(None))) else v
                     for k, v in self.tags.items()},
        }

    def __enter__(self) -> "Span":
        self.span_id = next(_span_ids)
        parent = _current_span.get()
        self.parent_id = parent.span_id if parent is not None else None
        self.started_at = time.perf_counter()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration = time.perf_counter() - self.started_at
        if exc is not None:
            self.error_type = type(exc).__name__
            self.error_message = str(exc)
        _current_span.reset(self._token)
        _recent.append(self)
        for listener in list(_listeners):
            try:
                listener(self)
            except Exception:  # noqa: BLE001 — listeners never break traced code
                log.exception("trace listener failed")


class ActivitySource:
    def __init__(self, name: str):
        self.name = name

    def span(self, name: str, **tags: Any) -> Span:
        return Span(self.name, name, tags)


def get_activity_source(name: str) -> ActivitySource:
    source = _sources.get(name)
    if source is None:
        source = _sources[name] = ActivitySource(name)
    return source


def current_span() -> Optional[Span]:
    return _current_span.get()


def add_listener(listener: Callable[[Span], None]) -> None:
    _listeners.append(listener)


def remove_listener(listener: Callable[[Span], None]) -> None:
    if listener in _listeners:
        _listeners.remove(listener)


def recent_spans(source: Optional[str] = None, name: Optional[str] = None) -> List[Span]:
    return [
        s
        for s in _recent
        if (source is None or s.source == source) and (name is None or s.name == name)
    ]


def clear_recent() -> None:
    """Drop the recorded span buffer. The buffer (and the listener list)
    are module-level state that would otherwise LEAK across tests — a span
    recorded by one test shows up in the next test's ``recent_spans()``.
    ``tests/conftest.py`` calls this per test (and snapshots/restores the
    listener list) so span assertions are hermetic."""
    _recent.clear()
