"""FusionMonitor — registry access sampling + periodic stats.

Re-expression of src/Stl.Fusion/Diagnostics/FusionMonitor.cs:7-100: samples
ComputedRegistry events (access = reads, register = computes) and reports
hit ratios; the number the reference's benchmark brags about is exactly
``hits / accesses``.
"""
from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from ..core.hub import FusionHub

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["FusionMonitor"]


class FusionMonitor:
    def __init__(self, hub: "FusionHub", report_period: float = 60.0, resilience=None):
        self.hub = hub
        self.report_period = report_period
        self._slow_accesses = 0
        self.registrations = 0
        self.invalidations = 0
        #: ResilienceEvents ledger exported by report(); defaults to the
        #: process-wide registry so breaker transitions, watchdog fallbacks
        #: and oplog quarantines show up with zero wiring
        if resilience is None:
            from ..resilience.events import global_events

            resilience = global_events()
        self.resilience = resilience
        #: RPC hubs whose fan-out/coalescer counters report() exports
        #: (attach_rpc_hub); weakly referenced so a monitor never pins a
        #: stopped hub's peer machinery
        self._rpc_hubs: list = []
        # the hot-cache fast path counts amortized on the registry (every
        # 16th hit — see core/service.py) instead of firing a hook per hit
        self._fast_hits0 = getattr(hub.registry, "fast_hits", 0)
        self._started_at = time.monotonic()
        self._last_report = self._started_at
        self._disposed = False
        hub.registry.on_access.append(self._on_access)
        hub.registry.on_register.append(self._on_register)
        hub.invalidated_hooks.append(self._on_invalidated)

    def dispose(self) -> None:
        """Detach all three hub hooks (idempotent). Without this every
        constructed monitor kept counting — and kept ITSELF alive through
        the hub's hook lists — forever."""
        if self._disposed:
            return
        self._disposed = True
        for hooks, fn in (
            (self.hub.registry.on_access, self._on_access),
            (self.hub.registry.on_register, self._on_register),
            (self.hub.invalidated_hooks, self._on_invalidated),
        ):
            try:
                hooks.remove(fn)
            except ValueError:
                pass

    def attach_rpc_hub(self, rpc_hub) -> "FusionMonitor":
        """Export an RPC hub's invalidation fan-out counters (per-peer
        outbox coalescing, batch frames, fanout-index drains) in
        :meth:`report` under ``"fanout"``."""
        import weakref

        self._rpc_hubs.append(weakref.ref(rpc_hub))
        return self

    def _fanout_report(self):
        totals = None
        for ref in self._rpc_hubs:
            hub = ref()
            if hub is None:
                continue
            stats = hub.fanout_stats()
            if totals is None:
                totals = stats
            else:
                for k, v in stats.items():
                    if isinstance(v, dict):  # nested fanout_index counters
                        sub = totals.setdefault(k, {})
                        for kk, vv in v.items():
                            if isinstance(vv, (int, float)):
                                sub[kk] = sub.get(kk, 0) + vv
                    elif isinstance(v, (int, float)):
                        totals[k] = totals.get(k, 0) + v
        return totals

    @property
    def accesses(self) -> int:
        fast = getattr(self.hub.registry, "fast_hits", 0) - self._fast_hits0
        return self._slow_accesses + fast

    # computes (misses) register; everything else that probed was a hit
    @property
    def hits(self) -> int:
        return max(self.accesses - self.registrations, 0)

    @property
    def hit_ratio(self) -> float:
        accesses = self.accesses
        return self.hits / accesses if accesses else 0.0

    def _on_access(self, _input) -> None:
        self._slow_accesses += 1
        now = time.monotonic()
        if now - self._last_report >= self.report_period:
            self._last_report = now
            log.info("fusion stats: %s", self.report())

    def _on_register(self, _computed) -> None:
        self.registrations += 1

    def _on_invalidated(self, _computed) -> None:
        self.invalidations += 1

    def report(self) -> dict:
        elapsed = time.monotonic() - self._started_at
        fanout = self._fanout_report()
        extra = {"fanout": fanout} if fanout is not None else {}
        return {
            **extra,
            "accesses": self.accesses,
            "computes": self.registrations,
            "invalidations": self.invalidations,
            "hit_ratio": round(self.hit_ratio, 4),
            "registry_size": len(self.hub.registry),
            "accesses_per_sec": round(self.accesses / elapsed, 1) if elapsed else 0.0,
            # degradation ledger: breaker transitions, watchdog fallbacks,
            # chaos injections, oplog quarantines — one dict of counters
            "resilience": self.resilience.snapshot(),
        }
