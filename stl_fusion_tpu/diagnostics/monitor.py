"""FusionMonitor — registry access sampling + periodic stats.

Re-expression of src/Stl.Fusion/Diagnostics/FusionMonitor.cs:7-100: samples
ComputedRegistry events (access = reads, register = computes) and reports
hit ratios; the number the reference's benchmark brags about is exactly
``hits / accesses``.
"""
from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from ..core.hub import FusionHub

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["FusionMonitor"]


class FusionMonitor:
    def __init__(
        self,
        hub: "FusionHub",
        report_period: float = 60.0,
        resilience=None,
        metrics=None,
    ):
        self.hub = hub
        self.report_period = report_period
        #: MetricsRegistry the report pulls shared telemetry from (the
        #: end-to-end delivery histogram the client apply path records);
        #: defaults to the process-wide registry
        if metrics is None:
            from .metrics import global_metrics

            metrics = global_metrics()
        self.metrics = metrics
        self._slow_accesses = 0
        self.registrations = 0
        self.invalidations = 0
        #: ResilienceEvents ledger exported by report(); defaults to the
        #: process-wide registry so breaker transitions, watchdog fallbacks
        #: and oplog quarantines show up with zero wiring
        if resilience is None:
            from ..resilience.events import global_events

            resilience = global_events()
        self.resilience = resilience
        #: RPC hubs whose fan-out/coalescer counters report() exports
        #: (attach_rpc_hub); weakly referenced so a monitor never pins a
        #: stopped hub's peer machinery
        self._rpc_hubs: list = []
        #: cluster control-plane parts (attach_cluster): member / router /
        #: rebalancer snapshots merged into report()["cluster"]
        self._cluster_parts: list = []
        #: edge gateway nodes (attach_edge): per-node snapshots listed in
        #: report()["edge"] — sessions, upstream subs, eviction/delivery
        self._edge_nodes: list = []
        #: mesh telemetry aggregator (attach_mesh_telemetry): fleet-scope
        #: snapshot table + stitched wave timelines via mesh_report()
        self._mesh_telemetry = None
        # the hot-cache fast path counts amortized on the registry (every
        # 16th hit — see core/service.py) instead of firing a hook per hit
        self._fast_hits0 = getattr(hub.registry, "fast_hits", 0)
        self._started_at = time.monotonic()
        self._last_report = self._started_at
        self._disposed = False
        self._reporter_task = None
        #: ConsistencyAuditor started by start_auditor(); its last_report
        #: surfaces as report()["audit"], and dispose() stops it
        self.auditor = None
        self._auditor_kwargs: dict = {}
        hub.registry.on_access.append(self._on_access)
        hub.registry.on_register.append(self._on_register)
        hub.invalidated_hooks.append(self._on_invalidated)

    def start_reporter(self, period: float = None):
        """Emit the periodic report from a BACKGROUND task instead of
        piggybacking on ``_on_access``: an idle-but-subscribed process
        (a server holding live ``$sys-c`` subscriptions with no local
        reads) never fires ``_on_access``, so it never reported at all.
        Requires a running event loop; idempotent while running; stopped
        for good by :meth:`dispose`."""
        import asyncio

        if self._disposed:
            raise RuntimeError("monitor is disposed")
        if self._reporter_task is not None and not self._reporter_task.done():
            return self._reporter_task
        if period is not None:
            self.report_period = period

        async def _report_loop():
            while True:
                await asyncio.sleep(self.report_period)
                self._last_report = time.monotonic()
                log.info("fusion stats: %s", self.report())

        self._reporter_task = asyncio.get_event_loop().create_task(_report_loop())
        return self._reporter_task

    def start_auditor(self, period: Optional[float] = None, **kwargs):
        """Start the online consistency auditor beside the reporter: sampled
        ``validate_hub``/``validate_mirror`` sweeps + the canary staleness
        sentinel, exporting ``fusion_invariant_violations`` /
        ``fusion_canary_staleness_ms`` and tripping a resilience-ledger
        event on violation (ISSUE 4). Idempotent while running — a repeat
        call with the same settings is a no-op returning the live task,
        and a new ``period`` retimes the running loop; CHANGED constructor
        settings raise instead of being silently dropped (a caller asking
        for ``sample=1.0`` must not keep auditing 25%). Stopped by
        :meth:`dispose`. Extra kwargs reach the
        :class:`~stl_fusion_tpu.diagnostics.auditor.ConsistencyAuditor`
        constructor (``sample=``, ``canary=``, ``backend=``, ...)."""
        if self._disposed:
            raise RuntimeError("monitor is disposed")
        if self.auditor is None:
            from .auditor import ConsistencyAuditor

            # defaults, not fixed arguments: the docstring promises kwargs
            # passthrough, so an explicit metrics=/events= must override
            # the monitor's own instead of raising a duplicate-kwarg error
            kwargs.setdefault("metrics", self.metrics)
            kwargs.setdefault("events", self.resilience)
            self._auditor_kwargs = dict(kwargs)
            self.auditor = ConsistencyAuditor(
                self.hub,
                period=period if period is not None else 30.0,
                **kwargs,
            )
        elif any(self._auditor_setting_differs(k, v) for k, v in kwargs.items()):
            raise RuntimeError(
                "auditor already constructed with different settings — "
                "adjust monitor.auditor directly, or dispose() and "
                "recreate the monitor"
            )
        return self.auditor.start(period=period)

    #: start_auditor kwarg → live ConsistencyAuditor attribute, for the
    #: changed-settings guard (a repeat call passing the value already in
    #: effect — even a constructor default — must stay a no-op)
    _AUDITOR_ATTRS = {
        "sample": "sample",
        "canary": "canary_enabled",
        "backend": "backend",
        "recorder": "recorder",
    }

    def _auditor_setting_differs(self, key: str, value) -> bool:
        if key in self._auditor_kwargs:
            return self._auditor_kwargs[key] != value
        attr = self._AUDITOR_ATTRS.get(key)
        if attr is not None:
            return getattr(self.auditor, attr) != value
        return True  # unrecorded setting (e.g. seed): conservative

    def dispose(self) -> None:
        """Detach all three hub hooks and stop the background reporter
        (idempotent). Without this every constructed monitor kept counting
        — and kept ITSELF alive through the hub's hook lists — forever."""
        if self._disposed:
            return
        self._disposed = True
        if self._reporter_task is not None:
            self._reporter_task.cancel()
            self._reporter_task = None
        if self.auditor is not None:
            self.auditor.dispose()
            self.auditor = None
        for hooks, fn in (
            (self.hub.registry.on_access, self._on_access),
            (self.hub.registry.on_register, self._on_register),
            (self.hub.invalidated_hooks, self._on_invalidated),
        ):
            try:
                hooks.remove(fn)
            except ValueError:
                pass

    def attach_rpc_hub(self, rpc_hub) -> "FusionMonitor":
        """Export an RPC hub's invalidation fan-out counters (per-peer
        outbox coalescing, batch frames, fanout-index drains) in
        :meth:`report` under ``"fanout"``."""
        import weakref

        self._rpc_hubs.append(weakref.ref(rpc_hub))
        return self

    def attach_cluster(self, *parts) -> "FusionMonitor":
        """Export cluster control-plane state in :meth:`report` under
        ``"cluster"``: any mix of ``ClusterMember``, ``ShardMapRouter``
        and ``ClusterRebalancer`` (anything with ``snapshot()``), merged
        into one dict. Weakly referenced, like the RPC hubs."""
        import weakref

        for part in parts:
            self._cluster_parts.append(weakref.ref(part))
        return self

    def attach_edge(self, *nodes) -> "FusionMonitor":
        """Export edge gateway state in :meth:`report` under ``"edge"``:
        one snapshot per attached :class:`~..edge.EdgeNode` (sessions,
        upstream subscriptions, evictions, resume/resubscribe counters,
        the fence→client-visible delivery histogram). Weakly referenced,
        like the RPC hubs."""
        import weakref

        for node in nodes:
            self._edge_nodes.append(weakref.ref(node))
        return self

    def attach_mesh_telemetry(self, aggregator) -> "FusionMonitor":
        """Export the mesh telemetry plane (ISSUE 18) through
        :meth:`mesh_report`: the aggregator's per-host snapshot table and
        the stitched cross-host wave timelines. Weakly referenced, like
        every other attachment."""
        import weakref

        self._mesh_telemetry = weakref.ref(aggregator)
        return self

    def mesh_report(self, cause=None) -> dict:
        """The mesh-scope answer ``report()`` cannot give: fleet snapshot
        freshness (per-host ages, stale/evicted marking) plus ONE stitched
        wave timeline — for ``cause``, or the most recent traced wave.
        Every field degrades explicitly: no aggregator attached reports
        ``"telemetry": None``, an unknown cause reports ``"trace": None``
        (with the cause it looked for) — never a silent empty dict."""
        from .mesh_telemetry import global_mesh_trace

        agg = self._mesh_telemetry() if self._mesh_telemetry is not None else None
        store = global_mesh_trace()
        looked_for = cause or store.latest_cause()
        stitched = None
        if looked_for is not None:
            stitched = store.stitch(
                looked_for,
                expected_hosts=agg.known_hosts() if agg is not None else None,
            )
        if stitched is not None and agg is not None:
            self._name_straggler_hotkeys(stitched, agg)
        return {
            "telemetry": agg.summary() if agg is not None else None,
            "cause": looked_for,
            "trace": stitched,
            # the judgment plane (ISSUE 19): mesh-scope verdict + merged
            # heavy hitters — degrade explicitly, same contract as above
            "health": agg.mesh_health() if agg is not None else None,
            "hotkeys": agg.hotkeys_report() if agg is not None else None,
        }

    @staticmethod
    def _name_straggler_hotkeys(stitched: dict, agg) -> None:
        """Attribution join (ISSUE 19): a slow shard names its hottest
        keys. The router's ``shard_keys`` sketch tracks routed calls as
        ``"<shard>|<service>.<method>"`` — each straggler row gets the
        top entries behind its own shard prefix."""
        rows = stitched.get("straggler") or ()
        if not rows:
            return
        try:
            sketch = agg.merged_sketches().get("shard_keys")
        except Exception:  # noqa: BLE001 — attribution is garnish, never a crash
            return
        if sketch is None:
            return
        entries = sketch.topk(sketch.capacity)
        for row in rows:
            prefix = f"{row.get('shard')}|"
            hot = [
                {"key": e["key"].partition("|")[2], "count": e["count"],
                 "share": e["share"]}
                for e in entries
                if e["key"].startswith(prefix)
            ][:3]
            if hot:
                row["hot_keys"] = hot

    def _edge_report(self):
        nodes = [ref() for ref in self._edge_nodes]
        snaps = [n.snapshot() for n in nodes if n is not None]
        return snaps or None

    def _cluster_report(self):
        merged = None
        for ref in self._cluster_parts:
            part = ref()
            if part is None:
                continue
            snap = part.snapshot()
            if merged is None:
                merged = dict(snap)
            else:
                merged.update(snap)
        return merged

    def _fanout_report(self):
        totals = None
        for ref in self._rpc_hubs:
            hub = ref()
            if hub is None:
                continue
            stats = hub.fanout_stats()
            if totals is None:
                totals = stats
            else:
                for k, v in stats.items():
                    if isinstance(v, dict):  # nested fanout_index counters
                        sub = totals.setdefault(k, {})
                        for kk, vv in v.items():
                            if isinstance(vv, (int, float)):
                                sub[kk] = sub.get(kk, 0) + vv
                    elif isinstance(v, (int, float)):
                        totals[k] = totals.get(k, 0) + v
        return totals

    @property
    def accesses(self) -> int:
        fast = getattr(self.hub.registry, "fast_hits", 0) - self._fast_hits0
        return self._slow_accesses + fast

    # computes (misses) register; everything else that probed was a hit
    @property
    def hits(self) -> int:
        return max(self.accesses - self.registrations, 0)

    @property
    def hit_ratio(self) -> float:
        accesses = self.accesses
        return self.hits / accesses if accesses else 0.0

    def _on_access(self, _input) -> None:
        self._slow_accesses += 1
        now = time.monotonic()
        if now - self._last_report >= self.report_period:
            self._last_report = now
            log.info("fusion stats: %s", self.report())

    def _on_register(self, _computed) -> None:
        self.registrations += 1

    def _on_invalidated(self, _computed) -> None:
        self.invalidations += 1

    def report(self) -> dict:
        elapsed = time.monotonic() - self._started_at
        fanout = self._fanout_report()
        extra = {"fanout": fanout} if fanout is not None else {}
        cluster = self._cluster_report()
        if cluster is not None:
            extra["cluster"] = cluster
        edge = self._edge_report()
        if edge is not None:
            extra["edge"] = edge
        # per-wave timelines: the hub's graph backend carries the profiler
        backend = getattr(self.hub, "graph_backend", None)
        profiler = getattr(backend, "profiler", None)
        if profiler is not None:
            # includes fused_depth_p50/p99 + timing_rejects (ISSUE 7): the
            # fused-path engagement and the negative-timing belt are part
            # of the standard waves report, not bench-only fields
            extra["waves"] = profiler.report()
        # nonblocking wave pipeline (ISSUE 7): accumulator depth, fused
        # dispatch count, eager/fault fallbacks, overlap occupancy
        pipeline = getattr(backend, "pipeline", None)
        if pipeline is not None:
            extra["pipeline"] = pipeline.stats()
        # end-to-end delivery: wave applied server-side -> client apply,
        # measured INSIDE the system (the $sys-c origin timestamp), not by
        # a harness. find(), not histogram(): reporting must never mint an
        # empty metric.
        delivery = self.metrics.find("fusion_e2e_delivery_ms")
        if delivery is not None:
            extra["delivery"] = delivery.snapshot()
        # causal flight journal: per-kind lifecycle counters + ring depth
        # (the events themselves serve via explain()/GET /explain)
        from .flight_recorder import RECORDER

        extra["recorder"] = RECORDER.summary()
        # online auditor: the latest sweep's verdict, when one is running
        if self.auditor is not None and self.auditor.last_report is not None:
            extra["audit"] = self.auditor.last_report
        # SLO verdict (ISSUE 19): the same machine-readable judgment
        # GET /health serves — mesh-scope when an aggregator is attached
        from .slo import global_slo_engine

        agg = self._mesh_telemetry() if self._mesh_telemetry is not None else None
        try:
            extra["health"] = (
                agg.mesh_health() if agg is not None
                else global_slo_engine().evaluate()
            )
        except Exception as e:  # noqa: BLE001 — a judging fault degrades, never raises
            extra["health"] = {"verdict": "degraded",
                               "error": {"type": type(e).__name__, "message": str(e)}}
        return {
            **extra,
            "accesses": self.accesses,
            "computes": self.registrations,
            "invalidations": self.invalidations,
            "hit_ratio": round(self.hit_ratio, 4),
            "registry_size": len(self.hub.registry),
            "accesses_per_sec": round(self.accesses / elapsed, 1) if elapsed else 0.0,
            # degradation ledger: breaker transitions, watchdog fallbacks,
            # chaos injections, oplog quarantines — one dict of counters
            "resilience": self.resilience.snapshot(),
        }
