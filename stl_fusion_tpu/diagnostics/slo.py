"""Declarative SLOs + multi-window burn-rate verdicts (ISSUE 19 tentpole).

The registry (metrics.py) measures; this module *judges*. An
:class:`SloSpec` binds a named objective to an existing registry series
(`fusion_e2e_delivery_ms` p99 ≤ budget, `fusion_superround_eager_rounds_total`
rate = 0, …) and owns the ONE comparator — :meth:`SloSpec.violated` — that
every consumer shares: the :class:`SloEngine` state machine behind
``GET /health``, ``FusionMonitor.report()["health"]``, and the perf-gate
``SloGate`` in perf/traffic_path.py. CI gates and ``/health`` can never
disagree about what "violated" means because they literally call the same
method.

The engine evaluates on demand (every ``/health`` hit, every mesh
telemetry publish) and keeps a bounded ring of observations per SLO. The
verdict is a multi-window burn-rate state machine in the SRE-workbook
style:

- **burning** (page): the violation fraction over the *fast* window
  crosses the fast ratio — the budget is burning NOW.
- **warn**: the *slow* window fraction crosses the slow ratio — a
  simmering problem that has not yet earned a page — or a just-recovered
  SLO still inside its hold-down (hysteresis: a verdict closes only after
  the fast window has been clean for ``hold_s``, so a flapping series
  cannot flap the page).
- **ok**: both windows clean and the hold-down elapsed.

Mesh scope: each host ships its local verdict inside the mesh telemetry
snapshot; :func:`merge_verdicts` folds them worst-wins, and a host whose
snapshot is stale contributes a **degraded** entry — stale is itself a
verdict, never silently healthy (the elastic-mesh lesson, ISSUE 16).

Windows and thresholds read their defaults from env
(``FUSION_SLO_FAST_S`` / ``FUSION_SLO_SLOW_S`` / ``FUSION_SLO_HOLD_S``,
``FUSION_SLO_DELIVERY_P99_MS`` / ``FUSION_SLO_SHED_RATE``) so the CI
smoke can compress minutes into seconds without forking the code path.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

__all__ = [
    "SloSpec",
    "SloEngine",
    "default_slos",
    "global_slo_engine",
    "merge_verdicts",
    "VERDICT_RANK",
]

#: severity order for merging — degraded (stale/unknown) outranks warn
#: because "we cannot see the host" is worse than "the host is simmering"
VERDICT_RANK: Dict[str, int] = {"ok": 0, "warn": 1, "degraded": 2, "burning": 3}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SloSpec:
    """One declarative objective bound to a registry series.

    ``kind`` selects how the engine observes the series:

    - ``"p99"``: 99th percentile of a registry histogram (ms).
    - ``"rate"``: per-second increase of a counter-like series (labeled
      collector samples summed over their base name).
    - ``"value"``: the instantaneous series value.

    ``comparator`` is how :meth:`violated` judges the observation against
    ``threshold``: ``"le"`` (healthy while value ≤ threshold, the default),
    ``"ge"`` (healthy while value ≥ threshold) or ``"eq"`` (healthy while
    value == threshold). ``attribution`` optionally names a hot-key domain
    (diagnostics/hotkeys.py) whose top entries ride along whenever this
    SLO is not ok — the verdict names its suspects.
    """

    __slots__ = (
        "name", "series", "kind", "threshold", "comparator",
        "description", "attribution", "unit",
    )

    def __init__(
        self,
        name: str,
        series: str = "",
        kind: str = "value",
        threshold: float = 0.0,
        comparator: str = "le",
        description: str = "",
        attribution: Optional[str] = None,
        unit: str = "",
    ):
        if kind not in ("p99", "rate", "value"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if comparator not in ("le", "ge", "eq"):
            raise ValueError(f"unknown SLO comparator {comparator!r}")
        self.name = name
        self.series = series
        self.kind = kind
        self.threshold = threshold
        self.comparator = comparator
        self.description = description
        self.attribution = attribution
        self.unit = unit

    def violated(self, value: Optional[float]) -> bool:
        """THE shared pass/fail comparator. ``None`` (a measurement that
        was attempted but produced nothing) counts as violated — a gate
        that measured nothing must fail loudly, not pass silently."""
        if value is None:
            return True
        if self.comparator == "eq":
            return value != self.threshold
        if self.comparator == "ge":
            return value < self.threshold
        return value > self.threshold


def default_slos() -> List[SloSpec]:
    """The shipped objectives (OBSERVABILITY.md §SLO catalog). Thresholds
    read env at call time so a harness can tighten/loosen per run."""
    return [
        SloSpec(
            "delivery_e2e_p99",
            series="fusion_e2e_delivery_ms",
            kind="p99",
            threshold=_env_float("FUSION_SLO_DELIVERY_P99_MS", 250.0),
            unit="ms",
            description="end-to-end invalidation delivery p99 within budget",
        ),
        SloSpec(
            "superround_eager_rounds",
            series="fusion_superround_eager_rounds_total",
            kind="rate",
            threshold=0.0,
            unit="/s",
            description="no rounds served by the counted eager fallback",
        ),
        SloSpec(
            "invariant_violations",
            series="fusion_invariant_violations",
            kind="value",
            threshold=0.0,
            unit="",
            description="the graph auditor has found zero invariant breaks",
        ),
        SloSpec(
            "edge_shed_rate",
            series="fusion_edge_shed_total",
            kind="rate",
            threshold=_env_float("FUSION_SLO_SHED_RATE", 0.5),
            unit="/s",
            attribution="tenant_sheds",
            description="admission shed rate within budget (per-tenant attribution)",
        ),
        SloSpec(
            "cmd_visible_p99",
            series="fusion_cmd_visible_ms",
            kind="p99",
            threshold=_env_float("FUSION_SLO_CMD_P99_MS", 250.0),
            unit="ms",
            description="command → client-visible invalidation p99 within budget",
        ),
        SloSpec(
            "cmd_error_rate",
            series="fusion_cmd_errors_total",
            kind="rate",
            threshold=_env_float("FUSION_SLO_CMD_ERROR_RATE", 0.0),
            unit="/s",
            description="no commands failing after bounded owner retries",
        ),
    ]


class _SloState:
    __slots__ = ("ring", "state", "state_since", "last_violation_t",
                 "last_value", "last_raw", "last_raw_t")

    def __init__(self):
        #: (t, value, violating) observations, pruned to the slow window
        self.ring: Deque[Tuple[float, Optional[float], bool]] = deque()
        self.state = "ok"
        self.state_since: Optional[float] = None
        self.last_violation_t: Optional[float] = None
        self.last_value: Optional[float] = None
        # rate-kind bookkeeping: previous raw counter reading
        self.last_raw: Optional[float] = None
        self.last_raw_t: Optional[float] = None


def _window_burn(
    ring: Deque[Tuple[float, Optional[float], bool]], t0: float
) -> Tuple[float, int]:
    """(violating fraction, sample count) over observations at/after t0."""
    n = 0
    bad = 0
    for t, _value, violating in ring:
        if t >= t0:
            n += 1
            if violating:
                bad += 1
    return (bad / n if n else 0.0), n


class SloEngine:
    """Evaluates a set of :class:`SloSpec` against a metrics registry and
    runs the multi-window burn-rate state machine per SLO."""

    def __init__(
        self,
        specs: Optional[List[SloSpec]] = None,
        registry: Optional[Any] = None,
        hotkeys: Optional[Any] = None,
        fast_s: Optional[float] = None,
        slow_s: Optional[float] = None,
        hold_s: Optional[float] = None,
        fast_ratio: float = 0.5,
        slow_ratio: float = 0.1,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        if registry is None:
            from .metrics import global_metrics

            registry = global_metrics()
        self.registry = registry
        self._hotkeys = hotkeys
        self.specs: List[SloSpec] = list(specs) if specs is not None else default_slos()
        self.fast_s = float(fast_s if fast_s is not None else _env_float("FUSION_SLO_FAST_S", 60.0))
        self.slow_s = float(slow_s if slow_s is not None else _env_float("FUSION_SLO_SLOW_S", 300.0))
        self.hold_s = float(hold_s if hold_s is not None else _env_float("FUSION_SLO_HOLD_S", self.fast_s))
        self.fast_ratio = float(fast_ratio)
        self.slow_ratio = float(slow_ratio)
        self.clock = clock
        self.wall = wall
        self.evaluations = 0
        self._lock = threading.Lock()
        self._states: Dict[str, _SloState] = {s.name: _SloState() for s in self.specs}
        registry.register_collector(self, SloEngine._collect_metrics)
        # per-SLO state is a rank, not a count: two engines at warn are at
        # warn, never at burning — scrape/merge as MAX (same contract as
        # fusion_superround_occupancy). Declared on the labeled series for
        # the in-process collector merge AND on the base name so the mesh
        # aggregator's base-name max set picks it up (mesh_telemetry.py).
        registry.set_aggregation("fusion_slo_state", "max")
        for spec in self.specs:
            registry.set_aggregation(f'fusion_slo_state{{slo="{spec.name}"}}', "max")

    # ------------------------------------------------------------------ observation
    def _observe(self, spec: SloSpec, st: _SloState, flat: Dict[str, float],
                 now: float) -> Tuple[Optional[float], bool]:
        """(value, have_observation) for one spec. Missing scalar series
        read as 0.0 (no shed counter means no sheds); an empty histogram
        yields NO observation (we cannot claim a latency we never saw)."""
        if spec.kind == "p99":
            h = self.registry.find(spec.series)
            if h is None or getattr(h, "count", 0) == 0:
                return None, False
            return h.percentile(99.0), True
        # scalar: sum flat samples over the base name (labeled collector
        # series like fusion_edge_shed_total{reason="..."} fold together)
        raw = 0.0
        for k, v in flat.items():
            if k == spec.series or k.partition("{")[0] == spec.series:
                raw += v
        if spec.kind == "value":
            return raw, True
        # rate: per-second increase since the previous evaluation
        prev_raw, prev_t = st.last_raw, st.last_raw_t
        st.last_raw, st.last_raw_t = raw, now
        if prev_raw is None or prev_t is None or now <= prev_t:
            return None, False  # first reading anchors the rate, no sample yet
        return max(0.0, raw - prev_raw) / (now - prev_t), True

    # ------------------------------------------------------------------ evaluate
    def evaluate(self) -> dict:
        """Take one observation per SLO, advance each state machine, and
        return the machine-readable local verdict (the ``/health`` body)."""
        now = self.clock()
        flat = self.registry.flat_samples()
        slos: List[dict] = []
        with self._lock:
            self.evaluations += 1
            for spec in self.specs:
                st = self._states[spec.name]
                value, have = self._observe(spec, st, flat, now)
                if have:
                    violating = spec.violated(value)
                    st.ring.append((now, value, violating))
                    st.last_value = value
                    if violating:
                        st.last_violation_t = now
                horizon = now - self.slow_s
                while st.ring and st.ring[0][0] < horizon:
                    st.ring.popleft()
                fast_frac, fast_n = _window_burn(st.ring, now - self.fast_s)
                slow_frac, slow_n = _window_burn(st.ring, horizon)
                prev = st.state
                if fast_n >= 2 and fast_frac >= self.fast_ratio:
                    state = "burning"
                elif slow_n >= 2 and slow_frac >= self.slow_ratio:
                    state = "warn"
                elif (
                    prev in ("burning", "warn")
                    and st.last_violation_t is not None
                    and (now - st.last_violation_t) < self.hold_s
                ):
                    state = "warn"  # hysteresis hold-down before closing
                else:
                    state = "ok"
                if state != prev:
                    st.state_since = now
                st.state = state
                entry = {
                    "name": spec.name,
                    "state": state,
                    "kind": spec.kind,
                    "series": spec.series,
                    "threshold": spec.threshold,
                    "unit": spec.unit,
                    "value": round(st.last_value, 4) if st.last_value is not None else None,
                    "burn": {
                        "fast": {"window_s": self.fast_s, "ratio": round(fast_frac, 4), "samples": fast_n},
                        "slow": {"window_s": self.slow_s, "ratio": round(slow_frac, 4), "samples": slow_n},
                    },
                }
                if state != "ok" and spec.attribution:
                    entry["attribution"] = {
                        "domain": spec.attribution,
                        "top": self._attribution(spec.attribution),
                    }
                slos.append(entry)
        worst = max(slos, key=lambda s: VERDICT_RANK.get(s["state"], 0), default=None)
        verdict = worst["state"] if worst is not None else "ok"
        return {
            "verdict": verdict,
            "scope": "local",
            "at": round(self.wall(), 3),
            "triggered_by": worst["name"] if worst is not None and verdict != "ok" else None,
            "slos": slos,
        }

    def _attribution(self, domain: str) -> List[dict]:
        board = self._hotkeys
        if board is None:
            from .hotkeys import global_hotkeys

            board = global_hotkeys()
        try:
            return board.topk(domain, 3)
        except Exception:  # noqa: BLE001 — attribution is garnish, never a crash
            return []

    # ------------------------------------------------------------------ telemetry
    def _collect_metrics(self) -> dict:
        with self._lock:
            out: Dict[str, float] = {
                "fusion_slo_evaluations_total": self.evaluations,
            }
            burning = 0
            for name, st in self._states.items():
                out[f'fusion_slo_state{{slo="{name}"}}'] = VERDICT_RANK.get(st.state, 0)
                if st.state == "burning":
                    burning += 1
            out["fusion_slo_burning"] = burning
            return out


def merge_verdicts(
    local: dict,
    remotes: Dict[str, Optional[dict]],
    stale_hosts: Optional[List[str]] = None,
    local_member: Optional[str] = None,
) -> dict:
    """Fold per-host verdicts into one mesh-scope verdict, worst-wins.

    ``remotes`` maps member → its last shipped local verdict (None when a
    host's snapshot predates the health plane). Every host in
    ``stale_hosts`` contributes a **degraded** entry regardless of what
    its stale snapshot claimed — a host we cannot see is never healthy."""
    stale = set(stale_hosts or ())
    hosts: Dict[str, dict] = {}
    worst_rank = -1
    worst_host: Optional[str] = None
    worst_slo: Optional[str] = None

    def _fold(member: str, verdict: Optional[dict], is_stale: bool) -> None:
        nonlocal worst_rank, worst_host, worst_slo
        if is_stale:
            entry = {
                "verdict": "degraded",
                "reason": "telemetry snapshot stale",
                "triggered_by": None,
            }
        elif not isinstance(verdict, dict):
            entry = {
                "verdict": "degraded",
                "reason": "no health verdict in snapshot",
                "triggered_by": None,
            }
        else:
            entry = {
                "verdict": verdict.get("verdict", "degraded"),
                "triggered_by": verdict.get("triggered_by"),
            }
        hosts[member] = entry
        rank = VERDICT_RANK.get(entry["verdict"], VERDICT_RANK["degraded"])
        if rank > worst_rank:
            worst_rank = rank
            worst_host = member
            worst_slo = entry.get("triggered_by")

    _fold(local_member or "local", local, False)
    for member in sorted(remotes):
        _fold(member, remotes[member], member in stale)
    for member in sorted(stale - set(remotes)):
        _fold(member, None, True)

    out = {
        "verdict": "ok" if worst_rank <= 0 else
        next(k for k, v in VERDICT_RANK.items() if v == worst_rank),
        "scope": "mesh",
        "at": local.get("at") if isinstance(local, dict) else None,
        "hosts": hosts,
        "stale": sorted(stale),
        "triggered_by": worst_slo if worst_rank > 0 else None,
        "triggered_host": worst_host if worst_rank > 0 else None,
        "slos": local.get("slos", []) if isinstance(local, dict) else [],
    }
    return out


_GLOBAL: Optional[SloEngine] = None
_GLOBAL_LOCK = threading.Lock()


def global_slo_engine() -> SloEngine:
    """The process-wide engine over ``global_metrics()`` and the default
    SLO catalog — ``/health`` and the mesh publisher evaluate here."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = SloEngine()
    return _GLOBAL
