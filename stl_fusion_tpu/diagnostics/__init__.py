"""Diagnostics (SURVEY.md §5.1): registry monitoring + hit-ratio reports,
activity-style tracing spans."""
from .monitor import FusionMonitor
from .tracing import (
    ActivitySource,
    Span,
    add_listener,
    current_span,
    get_activity_source,
    recent_spans,
    remove_listener,
)

__all__ = [
    "FusionMonitor",
    "ActivitySource",
    "Span",
    "add_listener",
    "current_span",
    "get_activity_source",
    "recent_spans",
    "remove_listener",
]
