"""Diagnostics (SURVEY.md §5.1-5.2): registry monitoring + hit-ratio
reports, activity-style tracing spans, explicit graph-invariant sweeps
(the build's race-detection story), the causal flight recorder +
``explain()`` introspection, and the online consistency auditor
(ISSUE 4).

NOTE: ``core.computed`` imports this package at module scope (the flight-
recorder hot-path hooks), so nothing here may import ``core``/``rpc`` at
module scope — ``explain``/``auditor`` keep those imports function-local.
"""
from .auditor import ConsistencyAuditor
from .explain import (
    explain,
    explain_client,
    explain_remote,
    explain_with_fallback,
    install_explain,
)
from .flight_recorder import RECORDER, FlightRecorder, global_recorder
from .hotkeys import HOTKEY_DOMAINS, HotKeyBoard, SpaceSavingSketch, global_hotkeys
from .invariants import InvariantReport, InvariantViolation, validate_hub, validate_mirror
from .mesh_telemetry import (
    MeshTelemetryAggregator,
    MeshTelemetryPublisher,
    MeshTelemetryService,
    MeshTraceStore,
    WaveSegment,
    global_mesh_trace,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    WaveProfiler,
    global_metrics,
)
from .monitor import FusionMonitor
from .slo import (
    SloEngine,
    SloSpec,
    default_slos,
    global_slo_engine,
    merge_verdicts,
)
from .tracing import (
    ActivitySource,
    Span,
    add_listener,
    clear_recent,
    current_cause_id,
    current_span,
    get_activity_source,
    recent_spans,
    remove_listener,
    span_cause_id,
)

__all__ = [
    "FusionMonitor",
    "ConsistencyAuditor",
    "FlightRecorder",
    "RECORDER",
    "global_recorder",
    "explain",
    "explain_client",
    "explain_remote",
    "explain_with_fallback",
    "install_explain",
    "MeshTelemetryAggregator",
    "MeshTelemetryPublisher",
    "MeshTelemetryService",
    "MeshTraceStore",
    "WaveSegment",
    "global_mesh_trace",
    "InvariantReport",
    "InvariantViolation",
    "validate_hub",
    "validate_mirror",
    "ActivitySource",
    "Span",
    "add_listener",
    "clear_recent",
    "current_cause_id",
    "current_span",
    "get_activity_source",
    "recent_spans",
    "remove_listener",
    "span_cause_id",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "WaveProfiler",
    "global_metrics",
    "HOTKEY_DOMAINS",
    "HotKeyBoard",
    "SpaceSavingSketch",
    "global_hotkeys",
    "SloEngine",
    "SloSpec",
    "default_slos",
    "global_slo_engine",
    "merge_verdicts",
]
