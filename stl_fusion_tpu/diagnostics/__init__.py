"""Diagnostics (SURVEY.md §5.1): registry monitoring + hit-ratio reports."""
from .monitor import FusionMonitor

__all__ = ["FusionMonitor"]
