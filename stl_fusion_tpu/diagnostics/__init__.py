"""Diagnostics (SURVEY.md §5.1-5.2): registry monitoring + hit-ratio
reports, activity-style tracing spans, and explicit graph-invariant sweeps
(the build's race-detection story)."""
from .invariants import InvariantReport, InvariantViolation, validate_hub, validate_mirror
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    WaveProfiler,
    global_metrics,
)
from .monitor import FusionMonitor
from .tracing import (
    ActivitySource,
    Span,
    add_listener,
    clear_recent,
    current_span,
    get_activity_source,
    recent_spans,
    remove_listener,
)

__all__ = [
    "FusionMonitor",
    "InvariantReport",
    "InvariantViolation",
    "validate_hub",
    "validate_mirror",
    "ActivitySource",
    "Span",
    "add_listener",
    "clear_recent",
    "current_span",
    "get_activity_source",
    "recent_spans",
    "remove_listener",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "WaveProfiler",
    "global_metrics",
]
