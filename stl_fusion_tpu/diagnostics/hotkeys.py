"""Workload attribution: cardinality-bounded heavy-hitter sketches (ISSUE 19).

When a tail blows up, the first operator question is "which key / which
tenant did this?" — and since the async frontier (ISSUE 17) removed the
per-level barrier, a stall no longer localizes itself. This module answers
the question with *space-saving* sketches (Metwally et al.): every hot path
offers its key, the sketch keeps at most ``capacity`` counters no matter
how many distinct keys pass through, and each surviving entry carries an
explicit over-count ``error`` bound so a consumer can tell a confident
heavy hitter from a lucky survivor.

Design rules, matching the registry's own (metrics.py):

- **Hot paths pay one dict hit.** ``offer()`` is a dict lookup + add on
  the common (already-tracked) path; eviction is amortized O(log k) via a
  lazy min-heap that tolerates stale entries and rebuilds itself when it
  grows past 4× capacity — memory stays O(k) under millions of distinct
  keys (tests/test_hotkeys.py drives 1M).
- **Merge is deterministic and commutative.** ``merge(a, b) == merge(b, a)``
  exactly: union the keys, sum per-sketch estimates and error bounds, keep
  the top ``capacity`` by ``(-count, key)``. That makes the sketches safe
  to ship inside mesh telemetry snapshots (mesh_telemetry.py) and fold at
  the aggregator in whatever order hosts report.
- **Counts are estimates, not truth.** A space-saving count may overstate
  by up to ``error``; it never understates. ``topk()`` reports both so
  ``explain()`` / ``/hotkeys`` can print honest shares.

The :class:`HotKeyBoard` groups one sketch per *domain* (wave
invalidations per node, edge deliveries per key, admission decisions per
tenant, routed calls per shard) and exports plain-counter telemetry
through the registry collector idiom — the sketches themselves travel in
mesh snapshots, not in the metric series.
"""
from __future__ import annotations

import heapq
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "SpaceSavingSketch",
    "HotKeyBoard",
    "global_hotkeys",
    "HOTKEY_DOMAINS",
]


class SpaceSavingSketch:
    """Bounded heavy-hitter counter (space-saving algorithm).

    Tracks at most ``capacity`` keys. Offering an untracked key when full
    evicts the current minimum-count entry deterministically (lowest
    count, ties by key) and inherits its count as the new key's error
    bound — the classic space-saving guarantee: a tracked count never
    understates the true count and overstates by at most ``error``.
    """

    __slots__ = ("capacity", "total", "_counts", "_errors", "_heap")

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        #: total offers seen (including evicted keys) — the share denominator
        self.total = 0
        self._counts: Dict[str, int] = {}
        self._errors: Dict[str, int] = {}
        #: lazy min-heap of (count, key); entries go stale when a key's
        #: count moves on — stale entries are skipped at pop time and the
        #: heap is rebuilt when it outgrows 4× capacity, keeping memory O(k)
        self._heap: List[Tuple[int, str]] = []

    def __len__(self) -> int:
        return len(self._counts)

    def offer(self, key: str, n: int = 1) -> None:
        n = int(n)
        if n <= 0:
            return
        self.total += n
        counts = self._counts
        c = counts.get(key)
        if c is not None:
            counts[key] = c + n
            heapq.heappush(self._heap, (c + n, key))
        elif len(counts) < self.capacity:
            counts[key] = n
            self._errors[key] = 0
            heapq.heappush(self._heap, (n, key))
        else:
            victim, vcount = self._pop_min()
            del counts[victim]
            self._errors.pop(victim, None)
            # inherit the victim's count: never understate, bound the lie
            counts[key] = vcount + n
            self._errors[key] = vcount
            heapq.heappush(self._heap, (vcount + n, key))
        if len(self._heap) > 4 * self.capacity:
            self._rebuild_heap()

    def _pop_min(self) -> Tuple[str, int]:
        counts = self._counts
        heap = self._heap
        while heap:
            count, key = heapq.heappop(heap)
            if counts.get(key) == count:
                return key, count
            # stale: the key was bumped (or already evicted) since this push
        # heap exhausted by staleness — fall back to a scan (rare; bounded O(k))
        key = min(counts, key=lambda k: (counts[k], k))
        return key, counts[key]

    def _rebuild_heap(self) -> None:
        self._heap = [(c, k) for k, c in self._counts.items()]
        heapq.heapify(self._heap)

    def estimate(self, key: str) -> int:
        """Estimated count for ``key`` (0 if untracked). Never understates
        the true count; overstates by at most :meth:`error_of`."""
        return self._counts.get(key, 0)

    def error_of(self, key: str) -> int:
        return self._errors.get(key, 0)

    def topk(self, n: int = 10) -> List[dict]:
        """Top-``n`` entries by ``(-count, key)`` with share-of-total."""
        total = self.total
        ranked = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return [
            {
                "key": k,
                "count": c,
                "error": self._errors.get(k, 0),
                "share": round(c / total, 6) if total else 0.0,
            }
            for k, c in ranked[: max(0, int(n))]
        ]

    def merge(self, other: "SpaceSavingSketch") -> "SpaceSavingSketch":
        """Commutative, deterministic merge: union keys, sum estimates and
        error bounds, truncate to capacity by ``(-count, key)``. A key kept
        by one sketch but absent from the other contributes that sketch's
        estimate alone (the absent side may have seen it and evicted it —
        that uncertainty is already inside the kept side's error bound)."""
        out = SpaceSavingSketch(max(self.capacity, other.capacity))
        out.total = self.total + other.total
        merged = sorted(
            (
                -(self._counts.get(k, 0) + other._counts.get(k, 0)),
                k,
                self._errors.get(k, 0) + other._errors.get(k, 0),
            )
            for k in set(self._counts) | set(other._counts)
        )
        for negc, k, e in merged[: out.capacity]:
            out._counts[k] = -negc
            out._errors[k] = e
        out._rebuild_heap()
        return out

    # ------------------------------------------------------------------ transport
    def to_payload(self) -> dict:
        """JSON-safe snapshot for mesh telemetry transport — entries sorted
        by ``(-count, key)`` so equal sketches serialize identically."""
        return {
            "capacity": self.capacity,
            "total": self.total,
            "entries": [
                [k, c, self._errors.get(k, 0)]
                for k, c in sorted(
                    self._counts.items(), key=lambda kv: (-kv[1], kv[0])
                )
            ],
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "SpaceSavingSketch":
        out = cls(int(payload.get("capacity") or 1))
        out.total = int(payload.get("total") or 0)
        for entry in payload.get("entries") or ():
            try:
                key, count, error = str(entry[0]), int(entry[1]), int(entry[2])
            except (TypeError, ValueError, IndexError):
                continue  # malformed wire entry: drop it, keep the sketch
            out._counts[key] = count
            out._errors[key] = error
        out._rebuild_heap()
        return out


#: the attribution domains the hot paths feed (OBSERVABILITY.md §Hot-key
#: attribution) — fixed vocabulary so mesh merge and /hotkeys rendering
#: agree on names without negotiation
HOTKEY_DOMAINS = (
    "wave_invalidations",  # graph waves: invalidations per node id (rpc/fanout.py)
    "edge_deliveries",     # edge fan-out: delivered frames per computed key
    "tenant_admits",       # admission: admitted requests per tenant
    "tenant_sheds",        # admission: shed requests per tenant
    "routed_shards",       # cluster router: routed calls per shard
    "shard_keys",          # cluster router: routed calls per shard|service.method
)


class HotKeyBoard:
    """One space-saving sketch per attribution domain, plus the plain
    offer counters the registry collector exports. Thread-safe: offers
    arrive from the asyncio loop, the edge fan shards, and the router."""

    def __init__(self, capacity: int = 64, registry: Optional[Any] = None):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._sketches: Dict[str, SpaceSavingSketch] = {}
        self.offers: Dict[str, int] = {}
        if registry is None:
            from .metrics import global_metrics

            registry = global_metrics()
        registry.register_collector(self, HotKeyBoard._collect_metrics)

    def offer(self, domain: str, key: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            sk = self._sketches.get(domain)
            if sk is None:
                sk = self._sketches[domain] = SpaceSavingSketch(self.capacity)
            sk.offer(key, n)
            self.offers[domain] = self.offers.get(domain, 0) + int(n)

    def sketch(self, domain: str) -> Optional[SpaceSavingSketch]:
        with self._lock:
            return self._sketches.get(domain)

    def domains(self) -> List[str]:
        with self._lock:
            return sorted(self._sketches)

    def topk(self, domain: str, n: int = 10) -> List[dict]:
        sk = self.sketch(domain)
        return sk.topk(n) if sk is not None else []

    def share_of(self, domain: str, key: str) -> Optional[dict]:
        """Attribution line for ``explain()``: the key's rank/share in the
        domain's top-k, or None when it is not a tracked heavy hitter."""
        sk = self.sketch(domain)
        if sk is None or sk.total <= 0:
            return None
        for rank, entry in enumerate(sk.topk(sk.capacity), start=1):
            if entry["key"] == key:
                return {
                    "domain": domain,
                    "rank": rank,
                    "count": entry["count"],
                    "error": entry["error"],
                    "share": entry["share"],
                }
        return None

    def _collect_metrics(self) -> dict:
        with self._lock:
            out: Dict[str, float] = {}
            for domain, n in self.offers.items():
                out[f'fusion_hotkey_offers_total{{domain="{domain}"}}'] = n
            for domain, sk in self._sketches.items():
                out[f'fusion_hotkey_tracked{{domain="{domain}"}}'] = len(sk)
            return out

    # ------------------------------------------------------------------ transport
    def payload(self) -> dict:
        """All domain sketches in wire shape (rides mesh telemetry
        snapshots under the ``"sketches"`` key)."""
        with self._lock:
            return {d: sk.to_payload() for d, sk in sorted(self._sketches.items())}

    @staticmethod
    def merge_payloads(payloads: List[dict]) -> Dict[str, SpaceSavingSketch]:
        """Fold any number of :meth:`payload` dicts (local + remote hosts)
        into merged per-domain sketches. Order-independent: the pairwise
        merge is commutative and associative-in-effect for the kept top-k
        (ties broken by key), and inputs are folded in sorted-domain order."""
        merged: Dict[str, SpaceSavingSketch] = {}
        for payload in payloads:
            if not isinstance(payload, dict):
                continue
            for domain in sorted(payload):
                sk = SpaceSavingSketch.from_payload(payload[domain])
                prev = merged.get(domain)
                merged[domain] = sk if prev is None else prev.merge(sk)
        return merged

    def report(self, n: int = 5, extra_payloads: Optional[List[dict]] = None) -> dict:
        """Top-``n`` per domain — ``/hotkeys`` and the bench digest shape.
        ``extra_payloads`` folds remote-host sketches in (mesh scope)."""
        if extra_payloads:
            merged = self.merge_payloads([self.payload()] + list(extra_payloads))
            return {
                d: {"total": sk.total, "top": sk.topk(n)}
                for d, sk in sorted(merged.items())
            }
        with self._lock:
            return {
                d: {"total": sk.total, "top": sk.topk(n)}
                for d, sk in sorted(self._sketches.items())
            }


_GLOBAL: Optional[HotKeyBoard] = None
_GLOBAL_LOCK = threading.Lock()


def global_hotkeys() -> HotKeyBoard:
    """The process-wide attribution board — hot paths offer here with no
    wiring, exactly like ``global_metrics()``."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = HotKeyBoard()
    return _GLOBAL
