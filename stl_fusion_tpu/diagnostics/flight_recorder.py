"""FlightRecorder — the causal flight journal (ISSUE 4 tentpole).

PR 3 made invalidation *latency* observable; this ring answers the
operator's second question — *why*: a bounded, lock-cheap journal of node
lifecycle events (registered / computed / invalidated / pruned / wave /
client-fenced / oplog-replayed), each stamped with the PR-3 cause id plus
— when the feeding layer knows them — the wave sequence number and the
oplog index. ``explain.py`` joins this ring against the wave-profiler
ring, the tracing span buffer and the CSR mirror to assemble a causal
chain ("X invalidated by wave W, caused by command C via oplog entry E,
fenced N clients").

Design rules, matching the metrics registry's:

- **Lock-cheap hot path**: one ``enabled`` check, a dict build, and ONE
  uncontended lock acquisition covering the ring append + the exact
  per-kind counters. The append stays INSIDE the lock on purpose:
  invalidation is multi-thread-safe, so a bare deque iteration racing a
  worker-thread append would raise "deque mutated during iteration"
  mid-``explain()``, and bare counter read-modify-writes would undercount.
  No I/O, no registry hop. Feeding sites additionally guard with
  ``if RECORDER.enabled:`` so a disabled recorder costs one attribute
  read — the same gate discipline as ``WaveProfiler.enabled``
  (``LIVE_RECORDER=0`` is the live-path A/B knob).
- **Bounded memory**: the ring holds ``capacity`` events (default 4096);
  a 100k-event storm keeps the newest 4096 and exact per-kind counters.
  Totals survive eviction, so the summary stays whole-run honest.
- **Context stamping without plumbing**: the graph backend publishes the
  wave seq it is currently applying (``current_wave``) and the oplog
  reader the record index it is currently replaying (``current_oplog``);
  ``note()`` auto-stamps both, so a ``Computed.invalidate_local`` deep in
  wave application never needs to thread identifiers through its callers.

Events are plain JSON-safe dicts — they travel verbatim through
``FusionMonitor.report()["recorder"]``, ``GET /explain`` and the
``$sys-d.explain`` cross-peer hop.
"""
from __future__ import annotations

import contextvars
import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "RECORDER",
    "global_recorder",
    "call_key",
    "method_key_fragment",
]


def method_key_fragment(method: str, args) -> str:
    """The method+args tail of a call-shaped journal key — the fragment
    the ``$sys-d`` string fallback matches against SERVER-side keys (whose
    class-name prefix differs from the RPC service name)."""
    return f".{method}{tuple(args)!r}"


def call_key(service: str, method: str, args) -> str:
    """THE call-shaped journal key: producer (client fence events in
    compute_call.py) and consumer (explain()'s key join) must build it
    through this one helper — byte-identical output is what makes
    ``for_key()`` find the events at all."""
    return f"{service}{method_key_fragment(method, args)}"

#: both stamping contexts are contextvars (like tracing spans), NOT plain
#: attributes: the oplog reader holds its stamp across awaits (an attribute
#: would mis-stamp events from OTHER tasks interleaved on the loop), and
#: wave application — though synchronous — can run while a WORKER THREAD
#: host-invalidates an unrelated node (invalidation is multi-thread-safe);
#: contextvars are per-thread/per-task, so neither ever sees the other's
#: stamp and explain() never attributes an event to a wave that did not
#: touch it
_current_oplog: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "fusion_current_oplog", default=None
)
_current_wave: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "fusion_current_wave", default=None
)


class FlightRecorder:
    def __init__(self, capacity: int = 4096):
        #: master gate — feeding sites check this BEFORE building the event
        self.enabled = True
        self.capacity = capacity
        self._ring: Deque[dict] = deque(maxlen=capacity)
        self._seq = itertools.count(1)
        #: per-kind totals; survive ring eviction (the 100k-storm contract).
        #: Guarded by a lock: invalidation is multi-thread-safe (per-node
        #: locks in core/computed.py), and a bare dict read-modify-write
        #: would lose increments across a GIL switch — "exact" means exact.
        #: Uncontended acquisition is ~100ns next to the ~2µs event build.
        self.counts: Dict[str, int] = {}
        self.events_recorded = 0
        self._count_lock = threading.Lock()

    # ------------------------------------------------------------------ context
    @property
    def current_wave(self) -> Optional[int]:
        """Wave seq the CURRENT THREAD/TASK is applying (contextvar-scoped:
        a worker thread's concurrent host-led invalidation must never be
        stamped with the loop thread's in-flight wave)."""
        return _current_wave.get()

    @current_wave.setter
    def current_wave(self, value: Optional[int]) -> None:
        _current_wave.set(value)

    @property
    def current_oplog(self) -> Optional[int]:
        """Oplog index the CURRENT TASK is replaying (contextvar-scoped —
        the reader holds it across awaits, so other tasks' events are
        never mis-stamped with an unrelated oplog index)."""
        return _current_oplog.get()

    @current_oplog.setter
    def current_oplog(self, value: Optional[int]) -> None:
        _current_oplog.set(value)

    # ------------------------------------------------------------------ feed
    def note(
        self,
        kind: str,
        key: Optional[str] = None,
        cause: Optional[str] = None,
        detail: Optional[str] = None,
        wave: Optional[int] = None,
        oplog: Optional[int] = None,
        count: Optional[int] = None,
    ) -> None:
        """Record one lifecycle event. Cheap by construction: dict build +
        deque append; callers gate on ``RECORDER.enabled`` so the disabled
        cost is a single attribute read at the call site. ``count`` is the
        structured multiplicity of the event (e.g. subscriptions fenced) —
        consumers must read it, never parse ``detail`` prose."""
        if not self.enabled:
            return
        ev: dict = {
            "seq": next(self._seq),
            "at": time.time(),
            "kind": kind,
            "key": key,
            "cause": cause,
        }
        wave = wave if wave is not None else _current_wave.get()
        if wave is not None:
            ev["wave"] = wave
        oplog = oplog if oplog is not None else _current_oplog.get()
        if oplog is not None:
            ev["oplog"] = oplog
        if count is not None:
            ev["count"] = count
        if detail is not None:
            ev["detail"] = detail
        with self._count_lock:
            # append under the same lock the query methods snapshot with:
            # a bare deque iteration racing a worker-thread append raises
            # "deque mutated during iteration" mid-explain()
            self._ring.append(ev)
            self.events_recorded += 1
            self.counts[kind] = self.counts.get(kind, 0) + 1

    # ------------------------------------------------------------------ query
    def _snapshot(self) -> List[dict]:
        """Stable copy of the ring for iteration — appends from another
        thread mid-query would otherwise raise "deque mutated during
        iteration" exactly when the system is busy."""
        with self._count_lock:
            return list(self._ring)

    def recent(self, n: Optional[int] = None, kind: Optional[str] = None) -> List[dict]:
        out = [e for e in self._snapshot() if kind is None or e["kind"] == kind]
        return out[-n:] if n is not None else out

    def for_key(self, key: str, limit: Optional[int] = None) -> List[dict]:
        """Events whose key matches exactly (chronological order)."""
        out = [e for e in self._snapshot() if e["key"] == key]
        return out[-limit:] if limit is not None else out

    def for_cause(self, cause: str, kind: Optional[str] = None) -> List[dict]:
        return [
            e
            for e in self._snapshot()
            if e["cause"] == cause and (kind is None or e["kind"] == kind)
        ]

    def keys_matching(self, fragment: str, limit: int = 32) -> List[str]:
        """Distinct recorded keys containing ``fragment`` (newest first) —
        the fallback resolver for ``GET /explain?key=`` string lookups."""
        seen: List[str] = []
        for e in reversed(self._snapshot()):
            k = e["key"]
            if k and fragment in k and k not in seen:
                seen.append(k)
                if len(seen) >= limit:
                    break
        return seen

    def summary(self) -> dict:
        with self._count_lock:  # consistent reads against worker-thread feeds
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "depth": len(self._ring),
                "events_recorded": self.events_recorded,
                "counts": dict(self.counts),
            }

    def report(self, recent: int = 32) -> dict:
        return {**self.summary(), "recent": self.recent(recent)}

    def clear(self) -> None:
        """Drop events, counters and context stamps (tests — mirrors
        ``tracing.clear_recent``; the conftest fixture isolates per test)."""
        with self._count_lock:
            self._ring.clear()
            self.counts.clear()
            self.events_recorded = 0
        _current_wave.set(None)
        _current_oplog.set(None)


#: the process-wide recorder: hot paths reference this singleton directly
#: (``if RECORDER.enabled: RECORDER.note(...)``) — never swapped, so the
#: bound references in core/graph/rpc stay valid for the process lifetime
RECORDER = FlightRecorder()


def global_recorder() -> FlightRecorder:
    """The process-wide flight recorder — same contract as
    ``metrics.global_metrics()`` / ``resilience.events.global_events()``."""
    return RECORDER
