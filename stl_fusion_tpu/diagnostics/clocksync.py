"""Cross-host clock alignment for delivery timestamps (ISSUE 9 satellite).

``fusion_e2e_delivery_ms`` (and the edge tier's ``fusion_edge_delivery_ms``
hop built on it) measures ``recv_perf_counter - origin_ts`` where
``origin_ts`` is the SENDER's ``perf_counter`` — trustworthy only when
both ends share a clock. Across hosts the two counters have unrelated
epochs, which OBSERVABILITY.md/EDGE.md carried as a shared open item and
the mesh exchange makes wrong BY CONSTRUCTION (a frontier crossing hosts
always lands on a foreign clock).

This module closes it with the standard NTP-style estimate, riding the
existing ``$sys`` channel (rpc/peer.py): a probe records
``(t_send, t_remote, t_recv)`` and the peer's offset is estimated at the
round trip's midpoint::

    offset(peer) = t_remote - (t_send + t_recv) / 2     # remote - local

keeping the MINIMUM-RTT sample (the one least contaminated by queueing —
Cristian's algorithm). ``to_local`` then maps a remote ``origin_ts`` onto
the local timeline before the histogram records it; the residual error is
bounded by RTT/2, a property the raw cross-host number never had. Peers
never probed (in-process transports, same-host stacks) fall back to the
identity mapping — exactly the old, correct-same-clock behavior.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional, Tuple

from .metrics import global_metrics

__all__ = ["ClockSync", "global_clock_sync"]


class ClockSync:
    """Per-peer clock-offset table (thread-safe; samples arrive on rpc
    pumps, reads happen on whatever loop applies the invalidation)."""

    def __init__(self):
        self._lock = threading.Lock()
        #: peer ref → (offset_s, rtt_s) of the best (min-RTT) sample
        self._offsets: Dict[str, Tuple[float, float]] = {}
        self.probes = 0
        global_metrics().register_collector(self, ClockSync._collect_metrics)

    def _collect_metrics(self) -> dict:
        out = {"fusion_clock_probes_total": self.probes}
        with self._lock:
            for ref, (off, rtt) in self._offsets.items():
                out[f'fusion_clock_offset_ms{{peer="{ref}"}}'] = off * 1e3
                out[f'fusion_clock_rtt_ms{{peer="{ref}"}}'] = rtt * 1e3
        return out

    # ------------------------------------------------------------------ samples
    def note_sample(self, ref: Optional[str], t_send: float, t_remote: float, t_recv: float) -> None:
        if ref is None:
            return
        rtt = max(t_recv - t_send, 0.0)
        offset = t_remote - (t_send + t_recv) / 2.0
        with self._lock:
            self.probes += 1
            best = self._offsets.get(ref)
            if best is None or rtt < best[1]:
                self._offsets[ref] = (offset, rtt)

    def forget(self, ref: str) -> None:
        """Retire one peer's sample — the mesh controller calls this for
        every member a re-form drops, so ``fusion_clock_offset_ms{peer=}``
        / ``fusion_clock_rtt_ms{peer=}`` series stop accumulating across
        re-forms and flaps (ISSUE 18 satellite: the per-peer label set was
        append-only before this)."""
        with self._lock:
            self._offsets.pop(ref, None)

    def prune(self, retired: "Iterable[str]") -> int:
        """Batch retire: drop every listed peer's sample, returning how
        many actually held one (a flap that re-joins re-probes fresh — the
        series set stays bounded by LIVE membership, not history)."""
        dropped = 0
        with self._lock:
            for ref in retired:
                if self._offsets.pop(ref, None) is not None:
                    dropped += 1
        return dropped

    # ------------------------------------------------------------------ mapping
    def offset(self, ref: Optional[str]) -> Optional[float]:
        if ref is None:
            return None
        with self._lock:
            best = self._offsets.get(ref)
        return best[0] if best is not None else None

    def rtt(self, ref: Optional[str]) -> Optional[float]:
        if ref is None:
            return None
        with self._lock:
            best = self._offsets.get(ref)
        return best[1] if best is not None else None

    def to_local(self, ref: Optional[str], remote_ts: float) -> float:
        """A remote perf_counter stamp on the LOCAL timeline. Identity for
        peers never probed (same-clock stacks keep the old exact path)."""
        off = self.offset(ref)
        return remote_ts if off is None else remote_ts - off

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "probes": self.probes,
                "peers": {
                    ref: {"offset_ms": off * 1e3, "rtt_ms": rtt * 1e3}
                    for ref, (off, rtt) in self._offsets.items()
                },
            }


_GLOBAL: Optional[ClockSync] = None
_GLOBAL_LOCK = threading.Lock()


def global_clock_sync() -> ClockSync:
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = ClockSync()
    return _GLOBAL


def now() -> float:
    """The clock every probe + delivery stamp uses (one place to swap)."""
    return time.perf_counter()
