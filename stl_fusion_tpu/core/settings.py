"""FusionSettings — global tuning derived from the host's CPU count.

Re-expression of src/Stl.Fusion/FusionSettings.cs:5-50: registry sizing uses
prime-adjacent capacities (fewer hash collisions), timer and pruner batch
sizes scale with a rounded-up power-of-two of the core count, and a
client/server mode flag picks smaller client-side defaults. Components read
these at construction; tests override the module-level ``settings``.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

__all__ = ["FusionMode", "FusionSettings", "settings"]


def _cpu_po2() -> int:
    n = os.cpu_count() or 1
    p = 1
    while p < n:
        p <<= 1
    return p


def _next_prime(n: int) -> int:
    def is_prime(x: int) -> bool:
        if x < 2:
            return False
        f = 2
        while f * f <= x:
            if x % f == 0:
                return False
            f += 1
        return True

    while not is_prime(n):
        n += 1
    return n


class FusionMode:
    SERVER = "server"
    CLIENT = "client"


@dataclass
class FusionSettings:
    mode: str = FusionMode.SERVER
    cpu_po2: int = field(default_factory=_cpu_po2)

    @property
    def registry_concurrency(self) -> int:
        """Lock striping level for the computed registry (prime-sized)."""
        return _next_prime(self.cpu_po2)

    @property
    def registry_capacity(self) -> int:
        """Initial registry capacity: prime near 512 (client) / 8k (server)
        per core-po2, matching the reference's client/server split."""
        base = 509 if self.mode == FusionMode.CLIENT else 8179
        return _next_prime(base * max(self.cpu_po2 // 4, 1))

    @property
    def timer_quanta(self) -> float:
        """Shared timer-wheel tick. The reference uses 0.2s quanta
        (Internal/Timeouts.cs); this build defaults finer — asyncio timers
        are cheap and sub-100ms invalidation delays are common in tests."""
        return 0.05

    @property
    def timer_concurrency(self) -> int:
        return max(self.cpu_po2 // 2, 1)

    @property
    def pruner_batch_size(self) -> int:
        return self.cpu_po2 * 512


settings = FusionSettings()
