"""ComputedInput — the cache key of a computed node.

Re-expression of src/Stl.Fusion/ComputedInput.cs:5-40 and
Interception/ComputeMethodInput.cs. An input identifies one memoization
slot: (function, service instance, normalized arguments). Inputs are
hashable, compare by value, and resolve their live node through the
registry (``get_existing_computed``).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any, Optional, Tuple

if TYPE_CHECKING:
    from .computed import Computed
    from .function import FunctionBase

__all__ = ["ComputedInput", "ComputeMethodInput", "KwArgsTail"]


class KwArgsTail:
    """Canonical keyword-argument tail of a cache key. Methods whose
    signature cannot be replayed positionally (keyword-only params, ``*``/
    ``**`` catch-alls) normalize to ``(*positional, KwArgsTail(sorted
    kwargs))`` — hashable, order-canonical, and replayable by
    :meth:`ComputeMethodInput.invoke_original` (a flat positional tuple
    would TypeError on replay; r4 review)."""

    __slots__ = ("items",)

    def __init__(self, items: Tuple):
        self.items = tuple(items)

    def __eq__(self, other: object) -> bool:
        return type(other) is KwArgsTail and self.items == other.items

    def __hash__(self) -> int:
        return hash(self.items)

    def __repr__(self) -> str:
        return f"**{dict(self.items)!r}"


class ComputedInput:
    """Abstract cache key; subclasses define equality/hash."""

    __slots__ = ("_hash",)

    @property
    def function(self) -> "FunctionBase":
        raise NotImplementedError

    def get_existing_computed(self) -> Optional["Computed"]:
        return self.function.hub.registry.get(self)

    def __hash__(self) -> int:
        return self._hash


class ComputeMethodInput(ComputedInput):
    """(method, service instance, args) — equality skips nothing because the
    decorator already strips non-key args (reference skips CancellationToken,
    ComputeMethodInput.cs:20-23)."""

    __slots__ = ("method_def", "service", "args", "_function")

    def __init__(self, method_def, service: Any, args: Tuple, function=None):
        self.method_def = method_def
        self.service = service
        self.args = args
        self._hash = hash((id(method_def), id(service), args))
        self._function = function

    @property
    def function(self) -> "FunctionBase":
        fn = self._function
        if fn is None:
            fn = self._function = self.method_def.get_function(self.service)
        return fn

    async def invoke_original(self):
        """Call the user's method body (≈ InvokeOriginalFunction,
        ComputeMethodInput.cs:32-45). A :class:`KwArgsTail` key tail —
        produced by bind_args for signatures that cannot be replayed
        positionally — is expanded back into keyword arguments."""
        args = self.args
        if args and type(args[-1]) is KwArgsTail:
            return await self.method_def.original(
                self.service, *args[:-1], **dict(args[-1].items)
            )
        return await self.method_def.original(self.service, *args)

    def __eq__(self, other: object) -> bool:
        return (
            type(other) is ComputeMethodInput
            and self.method_def is other.method_def  # type: ignore[union-attr]
            and self.service is other.service  # type: ignore[union-attr]
            and self.args == other.args  # type: ignore[union-attr]
        )

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:
        name = getattr(self.method_def, "name", "?")
        return f"{type(self.service).__name__}.{name}{self.args!r}"


def _register_kwargs_tail_wire() -> None:
    """KwArgsTail keys appear inside checkpointed node args (checkpoint/
    stores ``input.args`` verbatim), so they must round-trip the wire."""
    from ..utils.serialization import deep_tuple, register_wire_type

    register_wire_type(
        KwArgsTail,
        "KwArgsTail",
        to_dict=lambda v: {"i": [list(item) for item in v.items]},
        # key values must re-tuple DEEPLY or the restored key is
        # unhashable (r4 review)
        from_dict=lambda d: KwArgsTail((k, deep_tuple(val)) for k, val in d["i"]),
    )


_register_kwargs_tail_wire()
