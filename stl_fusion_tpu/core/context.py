"""ComputeContext / CallOptions — ambient call modes + dependency capture.

Re-expression of src/Stl.Fusion/ComputeContext.cs:6-91, CallOptions.cs and
the ``Computed`` statics (Computed.Static.cs:13-191). The reference flows
these through AsyncLocal; here they ride contextvars, which propagate across
``await`` exactly like AsyncLocal flows across continuations.

Two ambient slots:
- the **current context** — flags saying how compute-method calls behave
  (normal / peek-existing / invalidate / capture);
- the **current computed** — the node being computed right now, i.e. the
  root every nested compute-method call attaches a dependency edge to.

The ``invalidating()`` scope is the reference's ``using Computed.Invalidate()``
idiom: inside it, calling a compute method invalidates its cached node
instead of computing (the command-replay mechanism of the operations
framework rides on this).
"""
from __future__ import annotations

import contextlib
import contextvars
import enum
from typing import TYPE_CHECKING, Any, Awaitable, Callable, Optional, TypeVar

if TYPE_CHECKING:
    from .computed import Computed

T = TypeVar("T")

__all__ = [
    "CallOptions",
    "ComputeContext",
    "get_current",
    "change_current",
    "is_invalidating",
    "invalidating",
    "suspend_dependency_capture",
    "capture",
    "try_capture",
    "get_existing",
]


class CallOptions(enum.IntFlag):
    NONE = 0
    GET_EXISTING = 1
    INVALIDATE = 3  # implies GET_EXISTING (same bit layout as CallOptions.cs)
    CAPTURE = 4


# plain-int mirrors of the flag bits for the hot path — IntFlag's operator
# dispatch costs ~1 µs per `&`, which dominates the memoized-hit read
OPT_GET_EXISTING = 1
OPT_INVALIDATE_BIT = 2  # the bit that distinguishes INVALIDATE from GET_EXISTING
OPT_CAPTURE = 4


class ComputeContext:
    """Flags + a capture slot. Flyweight DEFAULT for the common case.

    ``call_options`` is stored as a plain ``int`` (not the IntFlag) so flag
    tests on the hot read path are single int ops.
    """

    __slots__ = ("call_options", "_captured", "invalidation_sink")

    DEFAULT: "ComputeContext"

    def __init__(self, call_options: CallOptions = CallOptions.NONE, invalidation_sink=None):
        self.call_options = int(call_options)
        #: when set (batch replay), INVALIDATE-mode hits are COLLECTED here
        #: instead of cascading host-side immediately — the caller applies
        #: them as one device lane burst (oplog/reader.py)
        self.invalidation_sink = invalidation_sink
        self._captured: Optional["Computed"] = None

    # -- capture ----------------------------------------------------------
    def try_capture(self, computed: "Computed") -> None:
        if self.call_options & OPT_CAPTURE and self._captured is None:
            self._captured = computed

    @property
    def captured(self) -> Optional["Computed"]:
        return self._captured

    # -- ambient access ---------------------------------------------------
    @staticmethod
    def current() -> "ComputeContext":
        return _current_context.get()

    @contextlib.contextmanager
    def activate(self):
        token = _current_context.set(self)
        try:
            yield self
        finally:
            _current_context.reset(token)

    def __repr__(self) -> str:
        return f"ComputeContext({CallOptions(self.call_options)!r})"


ComputeContext.DEFAULT = ComputeContext()

_current_context: contextvars.ContextVar[ComputeContext] = contextvars.ContextVar(
    "fusion_compute_context", default=ComputeContext.DEFAULT
)
_current_computed: contextvars.ContextVar[Optional["Computed"]] = contextvars.ContextVar(
    "fusion_current_computed", default=None
)


def get_current() -> Optional["Computed"]:
    """The node currently being computed — the dependency-capture root."""
    return _current_computed.get()


@contextlib.contextmanager
def change_current(computed: Optional["Computed"]):
    """Scope with a different (or no) dependency-capture root.

    Entering a compute body sets its node current AND resets the context to
    DEFAULT, so outer call modes (invalidate/capture) don't leak into nested
    calls (reference: ComputeMethodFunctionBase.cs:19-53).
    """
    t1 = _current_computed.set(computed)
    t2 = _current_context.set(ComputeContext.DEFAULT)
    try:
        yield
    finally:
        _current_context.reset(t2)
        _current_computed.reset(t1)


@contextlib.contextmanager
def suspend_dependency_capture():
    """Run a block without attaching dependencies to the current computed.

    ≈ the reference's ExecutionContext.SuppressFlow points
    (e.g. ClientComputeMethodFunction.cs:82).
    """
    token = _current_computed.set(None)
    try:
        yield
    finally:
        _current_computed.reset(token)


def is_invalidating() -> bool:
    return bool(_current_context.get().call_options & OPT_INVALIDATE_BIT)


class _InvalidatingScope:
    __slots__ = ("_ctx", "_cm", "_sink")

    def __init__(self, sink=None):
        self._sink = sink

    def __enter__(self):
        self._ctx = ComputeContext(CallOptions.INVALIDATE, invalidation_sink=self._sink)
        self._cm = self._ctx.activate()
        self._cm.__enter__()
        return self._ctx

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


def invalidating(sink=None) -> _InvalidatingScope:
    """``with invalidating(): await service.get(x)`` invalidates the cached
    node for ``get(x)`` instead of computing it.

    ``sink``: a list — INVALIDATE-mode hits are APPENDED instead of
    cascading immediately; the caller owns applying the collected group
    (e.g. as one lane of a device burst). Used by the op-log reader to
    lane-pack a batch of external operations' replays."""
    return _InvalidatingScope(sink)


async def capture(fn: Callable[[], Awaitable[T]]) -> "Computed":
    """Run ``fn`` in capture mode and return the Computed it produced/hit.

    ≈ ``Computed.Capture`` (Computed.Static.cs). Raises if nothing was
    captured (fn made no compute-method call).
    """
    ctx = ComputeContext(CallOptions.CAPTURE)
    with ctx.activate():
        await fn()
    if ctx.captured is None:
        raise RuntimeError("no computed was captured — did fn call a compute method?")
    return ctx.captured


async def try_capture(fn: Callable[[], Awaitable[Any]]) -> Optional["Computed"]:
    ctx = ComputeContext(CallOptions.CAPTURE)
    with ctx.activate():
        try:
            await fn()
        except Exception:  # noqa: BLE001 — errors are memoized; captured node carries them
            pass
    return ctx.captured


async def get_existing(fn: Callable[[], Awaitable[Any]]) -> Optional["Computed"]:
    """Peek the cached Computed for a call without computing (maybe stale).

    ≈ ``Computed.GetExisting`` (Computed.Static.cs).
    """
    ctx = ComputeContext(CallOptions.GET_EXISTING | CallOptions.CAPTURE)
    with ctx.activate():
        await fn()
    return ctx.captured
