"""FunctionBase — the compute driver: Read → Lock → RetryRead → Compute → Store.

Re-expression of src/Stl.Fusion/Function.cs:31-115 and
Internal/ComputedExt.cs:10-76. One FunctionBase exists per compute method /
state; ``invoke`` is the single entry point that:

1. READ — lock-free registry probe; a consistent hit registers the
   dependency edge and returns immediately (the 50M-ops/sec path in the
   reference's benchmark);
2. LOCK — per-input async lock so concurrent misses compute once
   (single-flight);
3. RETRY-READ — re-probe under the lock (someone may have computed while we
   waited);
4. COMPUTE — run the user body with this node as the ambient
   dependency-capture root;
5. STORE — register the node, attach the caller's edge, renew timers.

Call modes (CallOptions) divert before compute: INVALIDATE invalidates the
existing node and returns it; GET_EXISTING peeks without computing.
"""
from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Optional

from ..utils.ltag import LTag
from ..utils.result import Result
from .computed import Computed
from .context import (
    OPT_GET_EXISTING,
    OPT_INVALIDATE_BIT,
    CallOptions,
    ComputeContext,
    change_current,
)
from .options import ComputedOptions

if TYPE_CHECKING:
    from .hub import FusionHub
    from .inputs import ComputedInput

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["FunctionBase", "ComputeMethodFunction"]


class FunctionBase:
    def __init__(self, hub: "FusionHub", options: Optional[ComputedOptions] = None):
        self.hub = hub
        self.options = options or ComputedOptions.DEFAULT

    # ------------------------------------------------------------------ invoke
    async def invoke(
        self,
        input: "ComputedInput",
        used_by: Optional[Computed],
        context: Optional[ComputeContext] = None,
    ) -> Optional[Computed]:
        context = context or ComputeContext.current()

        # READ
        existing = self.hub.registry.get(input)
        hit = self._try_use_existing(existing, context, used_by)
        if hit is not None or context.call_options & OPT_GET_EXISTING:
            return hit

        # LOCK
        async with self.hub.registry.input_locks.lock(input):
            # RETRY-READ (peek: the same logical access as the READ above —
            # monitors must not count it twice)
            existing = self.hub.registry.peek(input)
            hit = self._try_use_existing_from_lock(existing, context, used_by)
            if hit is not None:
                return hit
            # COMPUTE + STORE
            computed = await self.compute(input, existing)
        self._use_new(computed, context, used_by)
        return computed

    async def invoke_and_strip(
        self,
        input: "ComputedInput",
        used_by: Optional[Computed],
        context: Optional[ComputeContext] = None,
    ):
        context = context or ComputeContext.current()
        computed = await self.invoke(input, used_by, context)
        if computed is None:
            return None
        if context.call_options & OPT_GET_EXISTING:
            # peek/invalidate modes return the (possibly stale) value without
            # raising memoized errors; callers wanting the node use capture
            out = computed._output
            return out.value_or_default if out is not None else None
        return computed.output.value

    # ------------------------------------------------------------------ hit paths
    def _try_use_existing(
        self,
        existing: Optional[Computed],
        context: ComputeContext,
        used_by: Optional[Computed],
    ) -> Optional[Computed]:
        opts = context.call_options
        if opts & OPT_INVALIDATE_BIT:
            if existing is not None:
                sink = context.invalidation_sink
                if sink is not None:
                    # batch replay: collect; the caller cascades the whole
                    # group on device in one lane burst
                    sink.append(existing)
                else:
                    existing.invalidate()
                context.try_capture(existing)
            return existing
        if opts & OPT_GET_EXISTING:
            if existing is not None:
                context.try_capture(existing)
                existing.renew_timeouts(False)
            return existing
        if existing is None or not existing.is_consistent:
            # note: is_consistent is pending-aware — a device-wave-invalidated
            # node reads as inconsistent here without host materialization;
            # the recompute's register() displacement finishes the cleanup
            # (graph/backend.py two-tier application)
            return None
        self._use_existing(existing, context, used_by)
        return existing

    def _try_use_existing_from_lock(
        self,
        existing: Optional[Computed],
        context: ComputeContext,
        used_by: Optional[Computed],
    ) -> Optional[Computed]:
        if existing is None or not existing.is_consistent:
            return None
        self._use_existing(existing, context, used_by)
        return existing

    def _use_existing(
        self, existing: Computed, context: ComputeContext, used_by: Optional[Computed]
    ) -> None:
        if used_by is not None:
            used_by.add_used(existing)
        existing.renew_timeouts(False)
        context.try_capture(existing)

    def _use_new(
        self, computed: Computed, context: ComputeContext, used_by: Optional[Computed]
    ) -> None:
        if used_by is not None:
            used_by.add_used(computed)
        computed.renew_timeouts(True)
        context.try_capture(computed)

    # ------------------------------------------------------------------ compute
    async def compute(self, input: "ComputedInput", existing: Optional[Computed]) -> Computed:
        version = self.hub.version_generator.next(existing.version if existing is not None else None)
        computed = self.create_computed(input, version)
        self.hub.registry.register(computed)
        with change_current(computed):
            try:
                value = await self.produce_value(input, computed)
                computed.try_set_output(Result.ok(value))
            except asyncio.CancelledError:
                # a cancelled compute never becomes a cached value
                computed.invalidate(immediately=True)
                raise
            except Exception as e:  # noqa: BLE001 — errors are memoized
                computed.try_set_output(Result.err(e))
        return computed

    def create_computed(self, input: "ComputedInput", version: LTag) -> Computed:
        return Computed(input, version, self.options)

    async def produce_value(self, input: "ComputedInput", computed: Computed):
        """Run the user computation; subclasses override."""
        raise NotImplementedError


class ComputeMethodFunction(FunctionBase):
    """FunctionBase over a ``@compute_method``-decorated body
    (≈ ComputeMethodFunction<T>, Interception/ComputeMethodFunctionBase.cs)."""

    def __init__(self, hub: "FusionHub", method_def):
        super().__init__(hub, method_def.options)
        self.method_def = method_def

    def create_computed(self, input, version):
        computed = super().create_computed(input, version)
        method_def = self.method_def
        if method_def.table is not None:
            args = getattr(input, "args", ())
            if method_def.table.covers(args):
                # scalar → table coherence rides the NODE, so every
                # invalidation path (invalidating() replay, dependency
                # cascade, timed/auto invalidation) marks the columnar row
                # stale — not just explicit replays. The table's own
                # handler finds this node already invalid, so no cycle.
                # The row resolves LAZILY (codec peek, never allocating):
                # the columnar side may intern this key only after the
                # node was created — or never, in which case there is no
                # row to mark.
                service = input.service

                def mark_row_stale(_node) -> None:
                    table = method_def.peek_table(service)
                    if table is not None:
                        row = method_def.row_for_args(args, table)
                        if row is not None:
                            table.invalidate([row])

                computed.on_invalidated(mark_row_stale)
        return computed

    async def produce_value(self, input, computed):
        return await input.invoke_original()

    def __repr__(self) -> str:
        return f"ComputeMethodFunction({self.method_def.name})"
