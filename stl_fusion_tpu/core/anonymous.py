"""AnonymousComputedSource — lambda-backed computed values, no service needed.

Re-expression of src/Stl.Fusion/AnonymousComputedSource.cs:13-80: the source
is simultaneously the ComputedInput (its own cache key) and the function that
computes it. Used directly and as the building block for State<T>.
"""
from __future__ import annotations

from typing import Awaitable, Callable, Generic, Optional, TypeVar

from .computed import Computed
from .context import ComputeContext, get_current
from .function import FunctionBase
from .hub import FusionHub, default_hub
from .inputs import ComputedInput
from .options import ComputedOptions

T = TypeVar("T")

__all__ = ["AnonymousComputedSource"]


class AnonymousComputedSource(ComputedInput, Generic[T]):
    __slots__ = ("_function", "computer", "name")

    def __init__(
        self,
        computer: Callable[["AnonymousComputedSource"], Awaitable[T]],
        hub: Optional[FusionHub] = None,
        options: Optional[ComputedOptions] = None,
        name: str = "anonymous",
    ):
        self.computer = computer
        self.name = name
        self._function = _AnonymousFunction(hub or default_hub(), self, options)
        self._hash = hash((id(self), name))

    @property
    def function(self) -> "FunctionBase":
        return self._function

    # identity key: each source is its own slot
    def __eq__(self, other: object) -> bool:
        return other is self

    def __hash__(self) -> int:
        return self._hash

    async def use(self) -> T:
        """Value with dependency registration on the ambient computing node."""
        computed = await self._function.invoke(self, used_by=get_current(), context=ComputeContext.current())
        return computed.output.value

    async def update(self) -> Computed[T]:
        return await self._function.invoke(self, used_by=None, context=ComputeContext.DEFAULT)

    @property
    def computed(self) -> Optional[Computed[T]]:
        return self.get_existing_computed()

    def invalidate(self) -> None:
        c = self.get_existing_computed()
        if c is not None:
            c.invalidate(immediately=True)

    def __repr__(self) -> str:
        return f"AnonymousComputedSource({self.name})"


class _AnonymousFunction(FunctionBase):
    def __init__(self, hub: FusionHub, source: AnonymousComputedSource, options: Optional[ComputedOptions]):
        super().__init__(hub, options)
        self.source = source

    async def produce_value(self, input, computed):
        return await self.source.computer(self.source)
