"""ConsistencyState — the 3-state computed lifecycle.

Re-expression of src/Stl.Fusion/ConsistencyState.cs:
Computing → Consistent → Invalidated, strictly forward.
The numeric values double as the node-state lane in the device CSR mirror
(stl_fusion_tpu.graph), so keep them stable.
"""
from __future__ import annotations

import enum

__all__ = ["ConsistencyState"]


class ConsistencyState(enum.IntEnum):
    COMPUTING = 0
    CONSISTENT = 1
    INVALIDATED = 2
