"""ComputedRegistry — THE graph store: weak interning map input → node.

Re-expression of src/Stl.Fusion/ComputedRegistry.cs:10-231. Holds a weak
reference per input (nodes die when nothing uses them — keep-alive timers and
dependents hold the strong refs), the per-input async locks that make
computation single-flight, and access/register events that feed diagnostics
(FusionMonitor) and the device-graph mirror.

The reference prunes dead GCHandles stochastically on an op counter; here
weakref callbacks remove entries eagerly, and ``prune()`` remains for edge
pruning sweeps (ComputedGraphPruner).
"""
from __future__ import annotations

import threading
import weakref
from typing import TYPE_CHECKING, Callable, List, Optional

from ..utils.async_utils import AsyncLockSet

if TYPE_CHECKING:
    from .computed import Computed
    from .inputs import ComputedInput

__all__ = ["ComputedRegistry"]


class ComputedRegistry:
    def __init__(self):
        self._map: dict = {}
        self._lock = threading.Lock()
        #: per-input single-flight compute locks (≈ InputLocks, ComputedRegistry.cs:31)
        self.input_locks = AsyncLockSet("compute")
        self.on_register: List[Callable[["Computed"], None]] = []
        self.on_unregister: List[Callable[["Computed"], None]] = []
        self.on_access: List[Callable[["ComputedInput"], None]] = []
        #: amortized count of memoized-hit FAST-path reads (the per-service
        #: hot cache bypasses ``get``/``on_access`` entirely; it bumps this
        #: by 16 on every 16th hit — the renewal cadence — so monitors keep
        #: a truthful access total without putting a hook on the hot path)
        self.fast_hits = 0

    def __len__(self) -> int:
        return len(self._map)

    def get(self, input: "ComputedInput") -> Optional["Computed"]:
        ref = self._map.get(input)
        computed = ref() if ref is not None else None
        for h in self.on_access:
            h(input)
        return computed

    def peek(self, input: "ComputedInput") -> Optional["Computed"]:
        """``get`` without the on_access hooks — internal bookkeeping probes
        (the hot-cache population after a miss, the under-lock RETRY-READ,
        the wrapper's pre-invoke check) must not multi-count one logical
        access in monitors."""
        ref = self._map.get(input)
        return ref() if ref is not None else None

    def count_access(self, input: "ComputedInput") -> None:
        """Fire the on_access hooks for an access served from a peek."""
        for h in self.on_access:
            h(input)

    def register(self, computed: "Computed") -> None:
        """Intern ``computed``; a displaced live entry is invalidated
        (reference Register, ComputedRegistry.cs:72-105)."""
        input = computed.input
        displaced: Optional["Computed"] = None
        with self._lock:
            old_ref = self._map.get(input)
            old = old_ref() if old_ref is not None else None
            if old is not None and old is not computed:
                displaced = old

            def _on_dead(ref, _input=input, _self=self):
                with _self._lock:
                    if _self._map.get(_input) is ref:
                        del _self._map[_input]

            self._map[input] = weakref.ref(computed, _on_dead)
        if displaced is not None and not displaced.is_invalidated:
            displaced.invalidate(immediately=True)
        for h in self.on_register:
            h(computed)

    def unregister(self, computed: "Computed") -> bool:
        with self._lock:
            ref = self._map.get(computed.input)
            if ref is None or ref() is not computed:
                return False
            del self._map[computed.input]
        for h in self.on_unregister:
            h(computed)
        return True

    def invalidate_everything(self) -> None:
        """(reference InvalidateEverything, ComputedRegistry.cs:142-147)"""
        with self._lock:
            refs = list(self._map.values())
        for ref in refs:
            c = ref()
            if c is not None:
                c.invalidate(immediately=True)

    def prune(self) -> int:
        """Drop dead refs + prune stale _usedBy edges of live nodes; returns
        edges removed (reference Prune, ComputedRegistry.cs:149-158 +
        ComputedGraphPruner sweep)."""
        with self._lock:
            items = list(self._map.items())
        removed_edges = 0
        for input, ref in items:
            c = ref()
            if c is None:
                with self._lock:
                    if self._map.get(input) is ref:
                        del self._map[input]
            else:
                removed_edges += c.prune_used_by()
        return removed_edges

    def live_computeds(self) -> List["Computed"]:
        with self._lock:
            return [c for ref in self._map.values() if (c := ref()) is not None]
