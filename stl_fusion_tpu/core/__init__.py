"""L1 — the computed-graph runtime (the heart).

Versioned memoized nodes, transparent dependency capture, cascading
invalidation. See SURVEY.md §2.1 for the reference component map this layer
re-expresses (src/Stl.Fusion)."""
from .anonymous import AnonymousComputedSource
from .computed import Computed
from .consistency import ConsistencyState
from .context import (
    CallOptions,
    ComputeContext,
    capture,
    change_current,
    get_current,
    get_existing,
    invalidating,
    is_invalidating,
    suspend_dependency_capture,
    try_capture,
)
from .function import ComputeMethodFunction, FunctionBase
from .hub import FusionHub, default_hub, set_default_hub
from .inputs import ComputedInput, ComputeMethodInput
from .options import ComputedOptions
from .pruner import ComputedGraphPruner
from .registry import ComputedRegistry
from .service import (
    ComputeMethodDef,
    ComputeService,
    InternKeyCodec,
    TableBacking,
    compute_method,
    hub_of,
    memo_table_of,
)
from .timeouts import Timeouts

__all__ = [
    "AnonymousComputedSource",
    "Computed",
    "ConsistencyState",
    "CallOptions",
    "ComputeContext",
    "capture",
    "change_current",
    "get_current",
    "get_existing",
    "invalidating",
    "is_invalidating",
    "suspend_dependency_capture",
    "try_capture",
    "ComputeMethodFunction",
    "FunctionBase",
    "FusionHub",
    "default_hub",
    "set_default_hub",
    "ComputedInput",
    "ComputeMethodInput",
    "ComputedOptions",
    "ComputedGraphPruner",
    "ComputedRegistry",
    "ComputeMethodDef",
    "ComputeService",
    "InternKeyCodec",
    "TableBacking",
    "compute_method",
    "memo_table_of",
    "hub_of",
    "Timeouts",
]
