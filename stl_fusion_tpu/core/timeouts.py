"""Timeouts — shared keep-alive + delayed-invalidation timer wheels.

Re-expression of src/Stl.Fusion/Internal/Timeouts.cs:3-34: two shared
``ConcurrentTimerSet``s. The keep-alive set holds a STRONG reference to each
computed until its ``min_cache_duration`` passes (that's the whole point —
without it, an unreferenced memoized node would be GC'd instantly); the
invalidate set fires ``computed.invalidate()`` for auto/delayed/transient-
error invalidation.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..utils.moment import MomentClock
from ..utils.timer_set import ConcurrentTimerSet

if TYPE_CHECKING:
    from .computed import Computed

__all__ = ["Timeouts"]


class Timeouts:
    def __init__(self, clock: MomentClock, quanta: float = 0.05):
        self.clock = clock
        # keep-alive: handler is a no-op — expiring just drops the strong ref
        self._keep_alive: ConcurrentTimerSet = ConcurrentTimerSet(
            lambda computed: None, quanta=quanta, clock=clock, name="keep-alive"
        )
        self._invalidate: ConcurrentTimerSet = ConcurrentTimerSet(
            lambda computed: computed.invalidate(immediately=True),
            quanta=quanta,
            clock=clock,
            name="invalidate",
        )

    def keep_alive(self, computed: "Computed", duration: float, now: Optional[float] = None) -> None:
        if now is None:
            now = self.clock.now()
        self._keep_alive.add_or_update_to_later(
            computed, now + duration, grid=duration / 64.0
        )

    def schedule_invalidate(self, computed: "Computed", delay: float) -> None:
        self._invalidate.add_or_update(computed, self.clock.now() + delay)

    def cancel(self, computed: "Computed") -> None:
        self._keep_alive.remove(computed)
        self._invalidate.remove(computed)

    def fire_all_due(self) -> None:
        """Synchronous tick for TestClock-driven tests."""
        self._invalidate.fire_all_due()
        self._keep_alive.fire_all_due()

    async def stop(self) -> None:
        await self._keep_alive.stop()
        await self._invalidate.stop()
