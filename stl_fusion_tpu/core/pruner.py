"""ComputedGraphPruner — background stale-edge sweep.

Re-expression of src/Stl.Fusion/Internal/ComputedGraphPruner.cs:5-111:
periodically walks the registry, drops dead weak entries, and prunes
``_used_by`` edges whose dependents no longer resolve to the recorded
version. Keeps the host graph (and therefore the device CSR mirror, which
rebuilds from it) from accumulating garbage under churn.
"""
from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from ..utils.async_chain import WorkerBase

if TYPE_CHECKING:
    from .hub import FusionHub

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["ComputedGraphPruner"]


class ComputedGraphPruner(WorkerBase):
    def __init__(self, hub: "FusionHub", check_period: float = 600.0, batch_size: int = 4096):
        super().__init__("computed-graph-pruner")
        self.hub = hub
        self.check_period = check_period
        self.batch_size = batch_size
        self.pruned_edges_total = 0

    async def on_run(self) -> None:
        while True:
            await self.hub.clocks.cpu.delay(self.check_period)
            removed = await self.prune_once()
            if removed:
                log.debug("graph pruner removed %d stale edges", removed)

    async def prune_once(self) -> int:
        """One full sweep, yielding between batches to stay off the hot path."""
        live = self.hub.registry.live_computeds()
        removed = 0
        for i, computed in enumerate(live):
            removed += computed.prune_used_by()
            if i % self.batch_size == self.batch_size - 1:
                await asyncio.sleep(0)
        removed += 0 if live else self.hub.registry.prune()
        self.pruned_edges_total += removed
        if removed:
            from ..diagnostics.flight_recorder import RECORDER

            if RECORDER.enabled:
                # one event per sweep, not per edge — the flight journal
                # answers "did pruning run, how much did it drop"
                RECORDER.note(
                    "pruned",
                    key="registry",
                    detail=f"{removed} stale used_by edges over {len(live)} nodes",
                )
        return removed
