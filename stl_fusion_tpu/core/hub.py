"""FusionHub — the composition root (≈ FusionBuilder + FusionInternalHub).

Re-expression of src/Stl.Fusion/FusionBuilder.cs:18-320 +
Internal/FusionInternalHub.cs, minus the DI container: a hub owns the
registry, version generator, clocks, timer wheels, the command pipeline
(attached by stl_fusion_tpu.commands), and the optional device-graph mirror
(attached by stl_fusion_tpu.graph). Services bind to a hub; a process-wide
default hub serves the common single-hub case.

The ``on_invalidated`` / ``on_edge_added`` hooks are the host→device feed:
the TPU graph backend subscribes here to keep the CSR mirror coherent with
the authoritative host graph.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional

from ..utils.ltag import LTagVersionGenerator, VersionGenerator
from ..utils.moment import MomentClockSet
from .registry import ComputedRegistry
from .settings import settings
from .timeouts import Timeouts

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["FusionHub", "default_hub", "set_default_hub"]


class FusionHub:
    def __init__(
        self,
        clocks: Optional[MomentClockSet] = None,
        version_generator: Optional[VersionGenerator] = None,
        timer_quanta: Optional[float] = None,
    ):
        self.clocks = clocks or MomentClockSet()
        self.version_generator = version_generator or LTagVersionGenerator()
        self.registry = ComputedRegistry()
        if timer_quanta is None:
            timer_quanta = settings.timer_quanta
        self.timeouts = Timeouts(self.clocks.cpu, quanta=timer_quanta)
        #: hooks feeding the device CSR mirror + diagnostics
        self.invalidated_hooks: List[Callable] = []
        self.edge_added_hooks: List[Callable] = []
        self._commander = None  # attached lazily by stl_fusion_tpu.commands
        self._graph_backend = None  # attached by stl_fusion_tpu.graph
        self._services: dict = {}

    # -- service container (minimal DI) -----------------------------------
    def add_service(self, service, key=None):
        """Register a service instance under its type (or an explicit key)."""
        self._services[key or type(service)] = service
        if hasattr(service, "_bind_hub"):
            service._bind_hub(self)
        return service

    def get_service(self, key):
        svc = self._services.get(key)
        if svc is None:
            if isinstance(key, type):
                # interface lookup: first registration whose type subclasses key
                for k, v in self._services.items():
                    if isinstance(k, type) and issubclass(k, key):
                        return v
            raise KeyError(f"service {key!r} is not registered in this hub")
        return svc

    # -- command pipeline --------------------------------------------------
    @property
    def commander(self):
        if self._commander is None:
            from ..commands.commander import Commander

            self._commander = Commander(self)
        return self._commander

    # -- device graph mirror ----------------------------------------------
    @property
    def graph_backend(self):
        return self._graph_backend

    def attach_graph_backend(self, backend) -> None:
        self._graph_backend = backend

    # -- nonblocking wave pipeline (ISSUE 7) ------------------------------
    @property
    def wave_pipeline(self):
        """The attached :class:`~stl_fusion_tpu.graph.WavePipeline`, or
        None while the hub runs blocking (one wave per dispatch)."""
        backend = self._graph_backend
        return getattr(backend, "pipeline", None) if backend is not None else None

    def enable_nonblocking(self, fuse_depth: int = 8, **kwargs):
        """Attach a nonblocking wave pipeline to the hub's graph backend:
        ``Computed.invalidate_eventually`` and the burst paths then
        accumulate seeds lazily and fuse consecutive waves into chained
        device dispatches, with fence fan-out overlapped against device
        execution (graph/nonblocking.py). Idempotent — returns the live
        pipeline when one is already attached. Requires a TpuGraphBackend
        (raises otherwise: with no device mirror there is nothing to
        fuse)."""
        backend = self._graph_backend
        if backend is None:
            raise RuntimeError(
                "enable_nonblocking needs a TpuGraphBackend attached to this hub"
            )
        if backend.pipeline is not None:
            return backend.pipeline
        from ..graph.nonblocking import WavePipeline

        return WavePipeline(backend, fuse_depth=fuse_depth, **kwargs)

    # -- host→device event feed -------------------------------------------
    def on_invalidated(self, computed) -> None:
        for h in self.invalidated_hooks:
            try:
                h(computed)
            except Exception:  # noqa: BLE001
                log.exception("invalidated hook failed")

    def on_edge_added(self, dependent, used) -> None:
        for h in self.edge_added_hooks:
            try:
                h(dependent, used)
            except Exception:  # noqa: BLE001
                log.exception("edge hook failed")


_default_hub: Optional[FusionHub] = None


def default_hub() -> FusionHub:
    global _default_hub
    if _default_hub is None:
        _default_hub = FusionHub()
    return _default_hub


def set_default_hub(hub: Optional[FusionHub]) -> Optional[FusionHub]:
    """Swap the process-default hub (tests use this for isolation)."""
    global _default_hub
    old = _default_hub
    _default_hub = hub
    return old
