"""Computed[T] — one memoized result + its edges in the dependency DAG.

Re-expression of src/Stl.Fusion/Computed.cs:28-450. A node is
``(input, version: LTag, output: Result, consistency_state)`` plus two edge
sets:
- ``_used`` — nodes this one depends on (STRONG refs: dependencies outlive
  dependents, Computed.cs:33);
- ``_used_by`` — ``(input, version)`` pairs of dependents (WEAK by design —
  resolved through the registry at invalidation time, and the version match
  means a recomputed dependent is never re-invalidated by a stale edge,
  Computed.cs:212-217).

Key invariants carried over from the reference:
- invalidation is idempotent and never raises (Computed.cs:220-229);
- a node invalidated while COMPUTING defers via ``invalidate_on_set_output``
  (the flag dance, Computed.cs:173-178);
- "dependencies that didn't finish aren't dependencies": adding an edge to an
  already-invalidated dependency invalidates the dependent instead
  (Computed.cs:347-363).

The cascade here is an explicit work-stack (no recursion limit); each node
invalidated also feeds the device-graph mirror via the hub hook, so the TPU
CSR copy stays coherent (stl_fusion_tpu.graph).
"""
from __future__ import annotations

import asyncio
import logging
import threading
from typing import TYPE_CHECKING, Any, AsyncIterator, Callable, Generic, List, Optional, Set, Tuple, TypeVar

from ..diagnostics.flight_recorder import RECORDER
from ..diagnostics.tracing import current_cause_id
from ..utils.ltag import LTag
from ..utils.result import Result
from .consistency import ConsistencyState
from .context import OPT_GET_EXISTING, CallOptions, ComputeContext, get_current
from .options import ComputedOptions

if TYPE_CHECKING:
    from .inputs import ComputedInput

T = TypeVar("T")
log = logging.getLogger("stl_fusion_tpu")

__all__ = ["Computed", "LAZY_WAVE_DETAIL"]

_INF = float("inf")

#: flight-journal detail stamped when a PENDING device-wave invalidation
#: (the unwatched lazy tier) is materialized on host — the wave's identity
#: is not recorded per-node (only the bit), but the MECHANISM is known and
#: explain() must not mislabel it "host-led" (diagnostics/explain.py keys
#: on this string)
LAZY_WAVE_DETAIL = "lazy device-wave invalidation materialized (wave identity not recorded per-node)"


class Computed(Generic[T]):
    __slots__ = (
        "input",
        "version",
        "options",
        "_state",
        "_output",
        "_used",
        "_used_by",
        "_invalidated_handlers",
        "_invalidate_on_set_output",
        "_delayed_invalidation_pending",
        "_lock",
        "_backend_nid",
        "_invalidation_cause",
        "_ka_renewed_until",
        "_ka_skip",
        "__weakref__",
    )

    def __init__(self, input: "ComputedInput", version: LTag, options: Optional[ComputedOptions] = None):
        self.input = input
        self.version = version
        self.options = options or ComputedOptions.DEFAULT
        self._state: int = int(ConsistencyState.COMPUTING)
        self._output: Optional[Result] = None
        self._used: Set["Computed"] = set()
        self._used_by: Set[Tuple["ComputedInput", LTag]] = set()
        self._invalidated_handlers: Optional[List[Callable[["Computed"], None]]] = None
        self._invalidate_on_set_output = False
        self._delayed_invalidation_pending = False
        self._lock = threading.Lock()
        self._backend_nid: Optional[int] = None  # device-mirror node id
        #: cause id of the wave/mutation that invalidated this node (ISSUE 3
        #: trace propagation) — stamped by the backend's eager apply; None
        #: for plain host-led invalidations outside any wave
        self._invalidation_cause: Optional[str] = None
        self._ka_renewed_until = 0.0  # keep-alive renewal throttle window
        self._ka_skip = 0  # hit-count renewal amortizer (see renew_timeouts)

    # ------------------------------------------------------------------ state
    def _pending_probe(self) -> bool:
        """True iff a device wave invalidated this node but the host hasn't
        materialized it yet (graph/backend.py lazy tier). Near-free when no
        device mirror is attached (``_backend_nid is None``)."""
        nid = self._backend_nid
        if nid is None:
            return False
        backend = self.input.function.hub._graph_backend
        return backend is not None and bool(backend._pending[nid])

    @property
    def consistency_state(self) -> ConsistencyState:
        if self._state == ConsistencyState.CONSISTENT and self._pending_probe():
            return ConsistencyState.INVALIDATED
        return ConsistencyState(self._state)

    @property
    def is_consistent(self) -> bool:
        return self._state == ConsistencyState.CONSISTENT and not self._pending_probe()

    @property
    def is_invalidated(self) -> bool:
        s = self._state
        return s == ConsistencyState.INVALIDATED or (
            s == ConsistencyState.CONSISTENT and self._pending_probe()
        )

    @property
    def output(self) -> Result:
        out = self._output
        if out is None:
            raise RuntimeError(f"{self!r} has no output yet (still computing)")
        return out

    @property
    def value(self) -> T:
        return self.output.value

    @property
    def error(self) -> Optional[BaseException]:
        out = self._output
        return out.error if out is not None else None

    def assert_consistency_state_is_not(self, state: ConsistencyState) -> None:
        if self._state == state:
            raise RuntimeError(f"{self!r}: unexpected consistency state {state.name}")

    # ------------------------------------------------------------------ output
    def try_set_output(self, output: Result) -> bool:
        """COMPUTING → CONSISTENT. False if the node already left COMPUTING.
        (reference: Computed.cs:141-160)"""
        with self._lock:
            if self._state != ConsistencyState.COMPUTING:
                return False
            self._output = output
            self._state = int(ConsistencyState.CONSISTENT)
            invalidate_now = self._invalidate_on_set_output
        if RECORDER.enabled:
            RECORDER.note("computed", key=repr(self.input))
        if invalidate_now:
            self.invalidate(immediately=True)
        else:
            self._start_auto_invalidation(output)
        return True

    def _start_auto_invalidation(self, output: Result) -> None:
        # errors are memoized too, but self-heal after a short delay
        # (reference: TransientErrorInvalidationDelay, ComputedOptions.cs)
        delay = (
            self.options.transient_error_invalidation_delay
            if output.has_error
            else self.options.auto_invalidation_delay
        )
        if delay == _INF:
            return
        if delay <= 0:
            self.invalidate(immediately=True)
        else:
            self._hub().timeouts.schedule_invalidate(self, delay)

    # ------------------------------------------------------------------ invalidation
    def invalidate(self, immediately: bool = False) -> bool:
        """Invalidate this node and cascade through ``_used_by``.

        Returns True if THIS call transitioned the node (idempotent, never
        raises — reference Computed.cs:162-230). Without ``immediately``, a
        configured ``invalidation_delay`` debounces the wave.
        """
        if self._state == ConsistencyState.INVALIDATED:
            return False
        if self._state == ConsistencyState.CONSISTENT and self._pending_probe():
            # a device wave already computed this node's transitive closure
            # (version-matched dependents included) — materialize locally,
            # no host cascade needed
            return self.invalidate_local(_detail=LAZY_WAVE_DETAIL)
        delay = self.options.invalidation_delay
        if not immediately and delay > 0:
            with self._lock:
                if self._state == ConsistencyState.INVALIDATED or self._delayed_invalidation_pending:
                    return False
                self._delayed_invalidation_pending = True
            self._hub().timeouts.schedule_invalidate(self, delay)
            return True

        transitioned = False
        # host-led cascades stamp their cause from the open tracing span
        # (the SAME id format device waves mint at _begin_wave), so an
        # explain() chain works even when no device mirror is attached
        host_cause = current_cause_id()
        stack: List["Computed"] = [self]
        while stack:
            node = stack.pop()
            with node._lock:
                state = node._state
                if state == ConsistencyState.INVALIDATED:
                    continue
                if state == ConsistencyState.COMPUTING:
                    # the flag dance: invalidate as soon as the output lands
                    node._invalidate_on_set_output = True
                    continue
                node._state = int(ConsistencyState.INVALIDATED)
                handlers = node._invalidated_handlers
                node._invalidated_handlers = None
                used = list(node._used)
                node._used.clear()
                used_by = list(node._used_by)
                node._used_by.clear()
            if node is self:
                transitioned = True
            if host_cause is not None:
                node._invalidation_cause = host_cause
            if RECORDER.enabled:
                RECORDER.note(
                    "invalidated", key=repr(node.input), cause=node._invalidation_cause
                )
            hub = node._hub()
            hub.timeouts.cancel(node)
            if handlers:
                for h in handlers:
                    try:
                        h(node)
                    except Exception:  # noqa: BLE001 — invalidation never throws
                        log.exception("invalidation handler failed for %r", node)
            # edge cleanup: we no longer depend on anything
            for u in used:
                u._remove_used_by(node)
            # cascade: version-matched dependents only
            for inp, ver in used_by:
                c = inp.get_existing_computed()
                if c is not None and c.version == ver:
                    stack.append(c)
            hub.on_invalidated(node)
        return transitioned

    def invalidate_eventually(self) -> bool:
        """GraphBLAS-style NONBLOCKING invalidate (ISSUE 7): enqueue this
        node as a seed in the hub's wave pipeline instead of cascading now.
        The transitive closure materializes when the pipeline's next fused
        chain is harvested — ``pipeline.drain()`` is the barrier; until
        then this node (and its dependents) still read consistent. The lazy
        accumulator batches seeds arriving between dispatches, so N calls
        cost one fused device dispatch, not N.

        Falls back to ``invalidate(immediately=True)`` when no pipeline is
        attached (``FusionHub.enable_nonblocking``), so call sites can
        adopt the nonblocking form unconditionally. Returns True when the
        invalidation was enqueued or applied."""
        backend = self.input.function.hub._graph_backend
        pipeline = getattr(backend, "pipeline", None) if backend is not None else None
        if pipeline is None:
            return self.invalidate(immediately=True)
        pipeline.submit([self])
        return True

    def invalidate_local(self, _detail: Optional[str] = None) -> bool:
        """Single-node invalidation WITHOUT cascading — used when a device
        wave already computed the full transitive closure and the host just
        applies it (stl_fusion_tpu.graph.TpuGraphBackend). ``_detail`` rides
        into the flight-journal event: lazy materializations pass
        :data:`LAZY_WAVE_DETAIL` so explain() can say "device wave,
        materialized lazily" instead of mislabeling them host-led."""
        with self._lock:
            state = self._state
            if state == ConsistencyState.INVALIDATED:
                return False
            if state == ConsistencyState.COMPUTING:
                self._invalidate_on_set_output = True
                return False
            self._state = int(ConsistencyState.INVALIDATED)
            handlers = self._invalidated_handlers
            self._invalidated_handlers = None
            used = list(self._used)
            self._used.clear()
            self._used_by.clear()
        if RECORDER.enabled:
            # cause was stamped by the backend's eager apply (device waves)
            # when one exists; the wave seq auto-stamps from the recorder's
            # current_wave context during wave application
            RECORDER.note(
                "invalidated",
                key=repr(self.input),
                cause=self._invalidation_cause,
                detail=_detail,
            )
        hub = self._hub()
        hub.timeouts.cancel(self)
        if handlers:
            for h in handlers:
                try:
                    h(self)
                except Exception:  # noqa: BLE001
                    log.exception("invalidation handler failed for %r", self)
        for u in used:
            u._remove_used_by(self)
        hub.on_invalidated(self)
        return True

    def on_invalidated(self, handler: Callable[["Computed"], None]) -> None:
        """Attach an invalidation handler; fires immediately if already invalid."""
        if self._state == ConsistencyState.CONSISTENT and self._pending_probe():
            # materialize the pending device invalidation so the handler
            # observes (and fires on) the real state
            self.invalidate_local(_detail=LAZY_WAVE_DETAIL)
        fire_now = False
        with self._lock:
            if self._state == ConsistencyState.INVALIDATED:
                fire_now = True
            else:
                if self._invalidated_handlers is None:
                    self._invalidated_handlers = []
                self._invalidated_handlers.append(handler)
        if not fire_now and self._backend_nid is not None:
            # device waves must apply this node eagerly now that someone
            # is observing it (graph/backend.py two-tier application)
            backend = self._hub().graph_backend
            if backend is not None:
                backend.mark_watched(self)
        if fire_now:
            try:
                handler(self)
            except Exception:  # noqa: BLE001
                log.exception("invalidation handler failed for %r", self)

    def when_invalidated(self) -> "asyncio.Future[Computed]":
        """Awaitable completing when this node is invalidated
        (≈ ComputedExt.WhenInvalidated, ComputedExt.cs:99-125)."""
        loop = asyncio.get_event_loop()
        fut: "asyncio.Future[Computed]" = loop.create_future()

        def handler(c: "Computed") -> None:
            def done() -> None:
                if not fut.done():
                    fut.set_result(c)

            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is loop:
                done()
            else:
                loop.call_soon_threadsafe(done)

        self.on_invalidated(handler)
        return fut

    # ------------------------------------------------------------------ edges
    def add_used(self, used: "Computed") -> None:
        """Record that THIS (computing) node depends on ``used``.

        Called on the dependent while its compute body runs
        (reference AddUsed/AddUsedBy, Computed.cs:347-377).
        """
        with self._lock:
            if self._state == ConsistencyState.INVALIDATED:
                return  # our wave already passed; edge is pointless
        if not used._try_add_used_by(self.input, self.version):
            # dependency already invalidated ⇒ we are stale before we finish
            self.invalidate(immediately=True)
            return
        with self._lock:
            if self._state == ConsistencyState.INVALIDATED:
                used._remove_used_by(self)
                return
            self._used.add(used)
        self._hub().on_edge_added(self, used)

    def _try_add_used_by(self, input: "ComputedInput", version: LTag) -> bool:
        with self._lock:
            if self._state == ConsistencyState.INVALIDATED:
                return False
            self._used_by.add((input, version))
            return True

    def _remove_used_by(self, dependent: "Computed") -> None:
        with self._lock:
            self._used_by.discard((dependent.input, dependent.version))

    def prune_used_by(self) -> int:
        """Drop ``_used_by`` edges whose dependent no longer resolves to the
        recorded version (reference PruneUsedBy, Computed.cs:400-419).
        Returns the number of edges removed."""
        with self._lock:
            stale = [
                e
                for e in self._used_by
                if (c := e[0].get_existing_computed()) is None or c.version != e[1]
            ]
            for e in stale:
                self._used_by.discard(e)
            return len(stale)

    @property
    def used(self) -> Tuple["Computed", ...]:
        with self._lock:
            return tuple(self._used)

    @property
    def used_by_count(self) -> int:
        with self._lock:
            return len(self._used_by)

    # ------------------------------------------------------------------ access
    def renew_timeouts(self, is_new: bool) -> None:
        """Refresh keep-alive on access (reference Computed.cs:248-262).

        Doubly amortized: (a) a hit-count skip — only every 16th access
        even LOOKS at the clock (≈ the reference's StochasticCounter-gated
        renewal, Computed.cs:248-262 + StochasticCounter.cs), so the
        memoized-hit fast path usually costs one int compare; (b) the timer
        wheel already snaps deadlines to a duration/64 grid, so renewals
        inside one grid cell cannot move the deadline. Worst case the
        deadline lags 16 accesses + one grid cell — the same slack class
        the reference's probabilistic renewal accepts."""
        if self._state == ConsistencyState.INVALIDATED:
            return
        if not is_new:
            k = self._ka_skip
            if k > 0:
                self._ka_skip = k - 1
                return
            self._ka_skip = 15
        d = self.options.min_cache_duration
        if d > 0:
            timeouts = self._hub().timeouts
            now = timeouts.clock.now()  # the HUB clock — TestClock-coherent
            if not is_new and now < self._ka_renewed_until:
                return
            self._ka_renewed_until = now + d / 64.0
            timeouts.keep_alive(self, d, now=now)

    async def update(self) -> "Computed[T]":
        """Return the latest consistent node for this input, recomputing if
        needed (reference Computed.Update, Computed.cs:277-295)."""
        if self.is_consistent:
            return self
        return await self.input.function.invoke(self.input, used_by=None, context=ComputeContext.DEFAULT)

    async def use(self) -> T:
        """Value of the latest consistent node, registering a dependency edge
        from the currently-computing node (reference Use, Computed.cs:297-305)."""
        ctx = ComputeContext.current()
        if ctx.call_options & OPT_GET_EXISTING:
            raise RuntimeError("Computed.use() is not allowed inside a peek/invalidate scope")
        usedby = get_current()
        if self.is_consistent:
            if usedby is not None:
                usedby.add_used(self)
            self.renew_timeouts(False)
            return self.output.value
        computed = await self.input.function.invoke(self.input, used_by=usedby, context=ctx)
        return computed.output.value

    async def when(self, predicate: Callable[[T], bool], poll_delay: float = 0.05) -> "Computed[T]":
        """Await a consistent node whose value satisfies ``predicate``
        (≈ ComputedExt.When, ComputedExt.cs:166-205)."""
        computed = self
        while True:
            computed = await computed.update()
            out = computed.output
            if not out.has_error and predicate(out.value):
                return computed
            await computed.when_invalidated()

    async def changes(self) -> AsyncIterator["Computed[T]"]:
        """Stream of consistent nodes over time
        (≈ ComputedExt.Changes, ComputedExt.cs:209-231)."""
        computed = self
        while True:
            computed = await computed.update()
            yield computed
            await computed.when_invalidated()

    # ------------------------------------------------------------------ internals
    def _hub(self):
        return self.input.function.hub

    def __repr__(self) -> str:
        return (
            f"Computed({self.input!r}, {self.version}, "
            f"{ConsistencyState(self._state).name})"
        )
