"""@compute_method + ComputeService — transparent memoization of async methods.

The TPU-native replacement for the reference's compile-time proxy machinery:
where Stl.Fusion generates ``{Name}Proxy`` classes via a Roslyn source
generator and intercepts virtual ``[ComputeMethod]`` calls
(Stl.Generators/ProxyGenerator.cs, Interception/ComputeServiceInterceptor.cs),
Python decorators wrap the method directly — same call path, zero codegen:

    class CartService(ComputeService):
        @compute_method
        async def get_total(self, cart_id: str) -> float: ...

Every call builds a ``ComputeMethodInput`` key, captures the ambient
currently-computing node as the dependency edge source, and runs the
Read→Lock→RetryRead→Compute→Store pipeline (see function.py).
"""
from __future__ import annotations

import functools
import inspect
import weakref
from typing import Any, Callable, Optional

from .context import OPT_INVALIDATE_BIT, CallOptions, ComputeContext, get_current
from .function import ComputeMethodFunction
from .hub import FusionHub, default_hub
from .inputs import ComputeMethodInput, KwArgsTail
from .options import ComputedOptions

__all__ = [
    "compute_method",
    "ComputeService",
    "ComputeMethodDef",
    "InternKeyCodec",
    "TableBacking",
    "hub_of",
    "memo_table_of",
]


class InternKeyCodec:
    """Arbitrary hashable call args ⇄ dense MemoTable row ids.

    The bridge that lets realistic key shapes — string user ids, composite
    (tenant, id) tuples — ride the columnar path (VERDICT r2 #5; ≈ the
    reference's DbEntityResolver batching arbitrary entity keys into dense
    batch slots, EntityFramework/DbEntityResolver.cs): keys are interned on
    first read, ``peek`` never allocates (invalidating a never-read key is
    a no-op, not a row burn), ``decode`` is the reverse map used by
    table→scalar invalidation and by the batch-refresh wrapper. Scoped like
    the MemoTable itself — per (service instance, hub) — so independent
    service instances with disjoint key universes each get the full row
    capacity (``TableBacking(keys=True)`` creates one codec per table; pass
    a codec INSTANCE to share a key→row layout deliberately)."""

    __slots__ = ("capacity", "_row_by_key", "_key_by_row")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._row_by_key: dict = {}
        self._key_by_row: list = []

    def peek(self, args: tuple) -> Optional[int]:
        return self._row_by_key.get(args)

    def acquire(self, args: tuple) -> int:
        row = self._row_by_key.get(args)
        if row is None:
            if len(self._key_by_row) >= self.capacity:
                raise KeyError(
                    f"key codec full ({self.capacity} rows interned); "
                    f"raise TableBacking(rows=...)"
                )
            row = len(self._key_by_row)
            self._row_by_key[args] = row
            self._key_by_row.append(args)
        return row

    def decode(self, row: int) -> Optional[tuple]:
        return self._key_by_row[row] if 0 <= row < len(self._key_by_row) else None

    def __len__(self) -> int:
        return len(self._key_by_row)


class TableBacking:
    """Declarative MemoTable backing for a dense-integer-key compute method.

    The TPU-first columnar twin of the scalar memoization slot (VERDICT r1
    weak #3: "nothing yet lets an ordinary ``@compute_method`` service
    transparently ride MemoTable"): declaring

        @compute_method(table=TableBacking(rows=1000, batch="get_many",
                                           row_shape=(2,)))
        async def get(self, uid: int): ...

    keeps the scalar call path EXACTLY as before (one Computed node per key,
    the reference's read pipeline) and additionally maintains one
    :class:`~..ops.memo_table.MemoTable` per (service, hub) whose rows are
    refreshed through the service's own ``batch`` method
    (``(ids: np.ndarray) -> rows``). The two stay coherent both ways:

    - invalidating the scalar method (``with invalidating(): await
      svc.get(k)`` — e.g. from a command's invalidation replay) also marks
      table row ``k`` stale;
    - ``table.invalidate(ids)`` also invalidates any LIVE scalar nodes for
      those keys (absent nodes cost nothing).

    Bulk reads ride ``memo_table_of(svc.get).read_batch(ids)`` — one device
    gather per batch, the public columnar path the read benchmark measures.

    Non-integer keys: ``keys=True`` (or an explicit codec object) interns
    arbitrary hashable call args into dense rows via
    :class:`InternKeyCodec`; bulk reads then go through
    ``memo_table_of(svc.get).read_keys(["alice", ...])`` and the ``batch``
    method receives the decoded KEYS (single-arg methods get bare keys,
    multi-arg methods get args tuples), not row ids.
    """

    __slots__ = (
        "rows", "batch", "row_shape", "dtype", "keys", "device_batch",
        "device_args",
    )

    def __init__(
        self, rows: int, batch: str, row_shape: tuple = (), dtype=None, keys=False,
        device_batch: Optional[str] = None, device_args: Optional[str] = None,
    ):
        self.rows = int(rows)
        self.batch = batch
        self.row_shape = tuple(row_shape)
        self.dtype = dtype
        #: False = dense int keys; True = one InternKeyCodec PER TABLE
        #: (per service instance × hub); a codec instance = shared layout
        self.keys = keys
        #: name of a jax-traceable method ``(ids, *args) -> rows`` — the
        #: DEVICE loader: stale-row refreshes then run entirely on device
        #: from the resident invalid state, zero host value traffic
        #: (TpuGraphBackend.refresh_block_on_device). Dense int keys only.
        #: ``device_args`` names a method returning the loader's device-
        #: array state, threaded through the program as RUNTIME args —
        #: closure-captured arrays would ride the compile payload as
        #: constants (hundreds of MB at scale; see ops/pull_wave.py).
        self.device_batch = device_batch
        self.device_args = device_args
        if device_batch is not None and keys:
            raise ValueError("device_batch requires dense int keys (keys=False)")

    def make_codec(self) -> Optional["InternKeyCodec"]:
        if self.keys is True:
            return InternKeyCodec(self.rows)
        return self.keys or None

    def covers(self, args: tuple) -> bool:
        """Could these call args EVER map to a table row? (A cheap shape
        check at node-creation time; the row itself resolves lazily at
        invalidation time through ``row_for_args``, which is the authority
        — including for normalized keys carrying a defaults tail.)"""
        if self.keys:
            return True
        return len(args) >= 1 and isinstance(args[0], int)


class ComputeMethodDef:
    """Per-method metadata + per-(hub) function cache
    (≈ ComputeMethodDef, Interception/ComputeMethodDef.cs)."""

    __slots__ = (
        "original", "name", "options", "signature", "table", "_functions",
        "_pos_defaults", "_n_required", "_hashable_defaults",
    )

    def __init__(self, original: Callable, options: ComputedOptions,
                 table: Optional[TableBacking] = None):
        self.original = original
        self.name = original.__qualname__
        self.options = options
        self.signature = inspect.signature(original)
        self.table = table
        self._functions: dict = {}
        # defaults tail for kwargs-free normalization (bind_args): only for
        # plain positional-or-keyword signatures. *args/**kwargs/keyword-
        # only methods normalize through signature.bind into a positional
        # prefix + KwArgsTail key (replayable — a flat positional tuple
        # would TypeError at invoke_original; r4 review).
        params = list(self.signature.parameters.values())[1:]  # drop self
        simple = all(
            p.kind is inspect.Parameter.POSITIONAL_OR_KEYWORD for p in params
        )
        self._pos_defaults = tuple(p.default for p in params) if simple else None
        # syntax guarantees defaults are a contiguous tail, so "the tail
        # from len(args) has no empty default" ⇔ len(args) ≥ required count
        self._n_required = sum(
            1 for p in params if p.default is inspect.Parameter.empty
        )
        # an UNHASHABLE default (b=[]) can never ride a cache key: keep the
        # old raw-args identity for such methods instead of crashing every
        # defaulted call at input-hash time (r4 review)
        try:
            hash(tuple(
                p.default for p in params
                if p.default is not inspect.Parameter.empty
            ))
            self._hashable_defaults = True
        except TypeError:
            self._hashable_defaults = False

    def get_function(self, service: Any) -> ComputeMethodFunction:
        hub = hub_of(service)
        fn = self._functions.get(id(hub))
        if fn is None:
            fn = ComputeMethodFunction(hub, self)
            self._functions[id(hub)] = fn
        return fn

    def get_table(self, service: Any):
        """The (service, hub)-scoped MemoTable, created on first use and
        wired for two-way invalidation coherence. Lazy so services that
        declare a backing but never take the columnar path pay nothing.
        Stored ON the service instance (not this class-lifetime def), so a
        dropped service releases its table — including the HBM values."""
        if self.table is None:
            raise TypeError(f"{self.name} has no table= backing declared")
        hub = hub_of(service)
        store = service.__dict__.setdefault("_fusion_memo_tables", {})
        key = (id(hub), self.name)
        table = store.get(key)
        if table is None:
            from ..ops.memo_table import MemoTable

            spec = self.table
            batch_fn = getattr(service, spec.batch)
            codec = spec.make_codec()  # PER-TABLE: instances don't share rows
            arity = len(self.signature.parameters) - 1  # minus self
            if codec is not None:
                # codec-backed tables refresh through KEYS: the service's
                # batch method sees what it declared (string ids, tuples),
                # never internal row numbers. Single-arg methods get bare
                # keys by DECLARED arity — a tuple-valued key of a 1-arg
                # method stays one key
                raw_batch = batch_fn

                def batch_fn(ids):
                    keys = []
                    for i in ids:
                        args = codec.decode(int(i))
                        if args is None:
                            raise KeyError(
                                f"row {int(i)} has no interned key — read "
                                f"codec-backed tables via read_keys()"
                            )
                        keys.append(args[0] if arity == 1 else args)
                    return raw_batch(keys)

            table = MemoTable(
                spec.rows, batch_fn, row_shape=spec.row_shape, dtype=spec.dtype
            )
            table.key_codec = codec
            table.key_arity = arity
            if spec.device_batch is not None:
                table.device_compute_fn = getattr(service, spec.device_batch)
                if spec.device_args is not None:
                    table.device_loader_args = getattr(service, spec.device_args)
            # table → scalar: a row invalidation reaches any LIVE scalar
            # node for that key (one registry probe per id; nodes that were
            # never read don't exist and cost nothing). node.invalidate()
            # is idempotent, which is what breaks the scalar↔table cycle.
            function = self.get_function(service)
            registry = hub.registry
            method_def = self

            def on_invalidate(ids) -> None:
                for i in ids:
                    args = method_def.args_for_row(int(i), table)
                    if args is None:
                        continue  # never-interned row: no scalar node exists
                    node = registry.get(
                        ComputeMethodInput(method_def, service, args, function)
                    )
                    if node is not None:
                        node.invalidate()

            table.on_invalidate.append(on_invalidate)
            store[key] = table
        return table

    def row_for_args(self, args: tuple, table) -> Optional[int]:
        """The row these call args map to in ``table``, WITHOUT allocating
        (invalidation paths: a key the columnar side never read has no row
        to mark). None when unmapped. The codec lives on the TABLE — it is
        per (service instance, hub), like the rows it allocates."""
        if self.table is None or table is None:
            return None
        codec = table.key_codec
        if codec is None:
            if len(args) == 1 and isinstance(args[0], int):
                return args[0]
            # normalized key of a defaulted method: (row, *defaults tail)
            # still maps to its row — dropping it here would sever scalar→
            # table invalidation coherence for every defaulted table method
            # (r4 review)
            d = self._pos_defaults
            if (
                d is not None
                and len(d) > 1
                and len(args) == len(d)
                and isinstance(args[0], int)
                and args[1:] == d[1:]
            ):
                return args[0]
            return None
        return codec.peek(tuple(args))

    def args_for_row(self, row: int, table) -> Optional[tuple]:
        """Canonical call args for a row of ``table`` (the reverse map used
        by table→scalar invalidation). Must return the NORMALIZED key —
        scalar nodes of a defaulted method register under
        ``(row, *defaults)``, so the short ``(row,)`` would miss them in
        the registry (r4 review)."""
        if self.table is None or table is None:
            return None
        codec = table.key_codec
        if codec is None:
            d = self._pos_defaults
            if (
                d is not None
                and len(d) > 1
                and self._n_required <= 1  # everything past the row defaults
                and self._hashable_defaults
            ):
                return (int(row),) + d[1:]
            return (int(row),)
        return codec.decode(int(row))

    def peek_table(self, service: Any):
        """The backing table if it was EVER materialized for this service
        (invalidations must not force-create a table nobody reads)."""
        if self.table is None:
            return None
        store = service.__dict__.get("_fusion_memo_tables")
        if store is None:
            return None
        return store.get((id(hub_of(service)), self.name))

    def bind_args(self, service: Any, args: tuple, kwargs: dict) -> tuple:
        """Normalize (args, kwargs) → one canonical cache key per logical
        call, so ``get(x=1)``, ``get(1)`` and ``get(1, b=default)`` share
        one slot (each shape keying its own node would let invalidation of
        one leave the others stale — r4 review). Plain positional-or-
        keyword signatures key a pure positional tuple (kwargs-free calls
        append the precomputed defaults tail — no ``signature.bind`` on the
        hot path); signatures with keyword-only or ``*``/``**`` params key
        ``(*positional, KwArgsTail)``, which invoke_original can replay.
        Calls omitting a REQUIRED argument pass through raw and fail at
        invocation, like any call."""
        d = self._pos_defaults
        if not kwargs and d is not None:
            if (
                len(args) >= len(d)
                or len(args) < self._n_required
                or not self._hashable_defaults
            ):
                return args
            return args + d[len(args):]
        try:
            bound = self.signature.bind(service, *args, **kwargs)
        except TypeError:
            # mis-shaped call: keep raw identity; invocation raises the
            # same TypeError the direct call would
            if kwargs:
                return args + (KwArgsTail(sorted(kwargs.items())),)
            return args
        if self._hashable_defaults:
            bound.apply_defaults()  # unhashable defaults must never key
        if d is not None:
            return tuple(bound.arguments.values())[1:]  # drop self
        pos = bound.args[1:]  # drop self
        kw = bound.kwargs
        return pos + ((KwArgsTail(sorted(kw.items())),) if kw else ())


def _make_hot_evictor(hot: dict, key):
    """Weakref finalizer dropping a hot-cache entry when its node is
    collected — without it, high-cardinality keyspaces would leak one
    (args-tuple → dead weakref) entry per key forever. Guarded by identity:
    a displaced-and-repopulated key must not lose its LIVE entry."""

    def evict(ref):
        if hot.get(key) is ref:
            del hot[key]

    return evict


def hub_of(service: Any) -> FusionHub:
    hub = getattr(service, "_fusion_hub", None)
    return hub if hub is not None else default_hub()


def memo_table_of(bound_method):
    """The MemoTable behind a table-backed compute method:
    ``memo_table_of(svc.get).read_batch(ids)`` is the public columnar read
    (one device gather per batch). Raises if the method has no ``table=``
    backing declared."""
    method_def = getattr(bound_method, "__compute_method_def__", None)
    service = getattr(bound_method, "__self__", None)
    if method_def is None or service is None:
        raise TypeError(f"{bound_method!r} is not a bound @compute_method")
    return method_def.get_table(service)


def compute_method(
    fn: Optional[Callable] = None,
    *,
    min_cache_duration: Optional[float] = None,
    auto_invalidation_delay: Optional[float] = None,
    invalidation_delay: Optional[float] = None,
    transient_error_invalidation_delay: Optional[float] = None,
    table: Optional[TableBacking] = None,
):
    """Decorator turning an async method into a memoized compute method.

    ≈ ``[ComputeMethod]`` (ComputeMethodAttribute.cs + ComputedOptions.cs
    resolution). Options map 1:1 onto ComputedOptions.
    """

    def decorate(func: Callable) -> Callable:
        if not inspect.iscoroutinefunction(func):
            raise TypeError(f"@compute_method requires an async def, got {func!r}")
        options = ComputedOptions.new(
            min_cache_duration=min_cache_duration,
            auto_invalidation_delay=auto_invalidation_delay,
            invalidation_delay=invalidation_delay,
            transient_error_invalidation_delay=transient_error_invalidation_delay,
        )
        method_def = ComputeMethodDef(func, options, table)
        # per-service HOT cache attribute: args → weakref(consistent node).
        # Weak entries keep the registry's lifecycle authoritative (pruner /
        # keep-alive expiry still collect nodes; a dead or inconsistent
        # entry just falls through to the full path and is re-populated).
        hot_attr = f"_fusion_hot_{func.__qualname__.replace('.', '_')}"

        @functools.wraps(func)
        async def wrapper(self, *args, **kwargs):
            context = ComputeContext.current()
            copts = context.call_options
            if copts == 0 and not kwargs:
                # memoized-hit FAST path (the reference's 50M-ops/sec READ,
                # Function.cs:56): default call mode + consistent node →
                # attach the edge and return with no input construction, no
                # registry probe, no awaits (≈1 dict hit + 1 weakref deref)
                hot = self.__dict__.get(hot_attr)
                if hot is not None:
                    ref = hot.get(args)
                    if ref is not None:
                        existing = ref()
                        if existing is not None and existing.is_consistent:
                            used_by = get_current()
                            if used_by is not None:
                                used_by.add_used(existing)
                            if existing._ka_skip == 0:
                                # every 16th hit (the renewal cadence):
                                # amortized access accounting for monitors
                                existing.input.function.hub.registry.fast_hits += 16
                            existing.renew_timeouts(False)
                            return existing._output.value
                        if existing is None:
                            hot.pop(args, None)  # collected (evictor may race)
            function = method_def.get_function(self)
            input = ComputeMethodInput(
                method_def, self, method_def.bind_args(self, args, kwargs), function
            )
            if copts == 0:
                registry = function.hub.registry
                # peek, not get: on a miss, invoke's own READ is the ONE
                # counted access — a get here would make every miss count
                # twice and read as a phantom hit in monitors
                existing = registry.peek(input)
                if existing is None or not existing.is_consistent:
                    value = await function.invoke_and_strip(input, get_current(), context)
                    existing = registry.peek(input)
                    if existing is None or not existing.is_consistent:
                        return value
                else:
                    registry.count_access(input)  # a served warm hit
                    used_by = get_current()
                    if used_by is not None:
                        used_by.add_used(existing)
                    existing.renew_timeouts(False)
                    value = existing.output.value
                hot = self.__dict__.get(hot_attr)
                if hot is None:
                    hot = self.__dict__[hot_attr] = {}
                key = input.args
                ref = weakref.ref(existing, _make_hot_evictor(hot, key))
                hot[key] = ref
                if not kwargs and args != key:
                    # the fast path probes by the RAW positional tuple; a
                    # call omitting defaulted params normalizes to a longer
                    # key (ADVICE r4) — alias the raw tuple to the same node
                    # so such calls fast-path too. SOUND only kwargs-free:
                    # the normalized key is then a pure function of the raw
                    # tuple. Kwargs calls never alias (get(1, b=3) raw-keys
                    # as (1,), which must stay free for the real get(1)) and
                    # are excluded from the fast path by design — they pay
                    # the slow path's registry probe, the documented cost.
                    hot[args] = weakref.ref(existing, _make_hot_evictor(hot, args))
                return value
            # the ambient computing node is the dependency-capture root —
            # except inside an invalidation replay, where no edges form.
            # scalar → table coherence lives on the node itself (see
            # ComputeMethodFunction.create_computed), so EVERY invalidation
            # path marks the columnar row stale — but a replay for a key
            # with NO live node must still reach the row (the columnar
            # cache exists independently of scalar nodes), handled here
            # without double-firing when a node does exist.
            invalidate_mode = bool(copts & OPT_INVALIDATE_BIT)
            node_existed = (
                function.hub.registry.get(input) is not None
                if invalidate_mode and method_def.table is not None
                else True
            )
            used_by = None if invalidate_mode else get_current()
            result = await function.invoke_and_strip(input, used_by, context)
            if invalidate_mode and method_def.table is not None and not node_existed:
                tbl = method_def.peek_table(self)
                if tbl is not None:
                    row = method_def.row_for_args(input.args, tbl)
                    if row is not None:
                        tbl.invalidate([row])
            return result

        wrapper.__compute_method_def__ = method_def  # type: ignore[attr-defined]
        return wrapper

    if fn is not None:
        return decorate(fn)
    return decorate


class ComputeService:
    """Optional base for compute services: explicit hub binding + helpers.

    Any class works with @compute_method; inheriting this adds hub plumbing
    (≈ IComputeService marker)."""

    _fusion_hub: Optional[FusionHub] = None

    def __init__(self, hub: Optional[FusionHub] = None):
        self._fusion_hub = hub

    def _bind_hub(self, hub: FusionHub) -> None:
        self._fusion_hub = hub
