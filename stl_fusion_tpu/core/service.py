"""@compute_method + ComputeService — transparent memoization of async methods.

The TPU-native replacement for the reference's compile-time proxy machinery:
where Stl.Fusion generates ``{Name}Proxy`` classes via a Roslyn source
generator and intercepts virtual ``[ComputeMethod]`` calls
(Stl.Generators/ProxyGenerator.cs, Interception/ComputeServiceInterceptor.cs),
Python decorators wrap the method directly — same call path, zero codegen:

    class CartService(ComputeService):
        @compute_method
        async def get_total(self, cart_id: str) -> float: ...

Every call builds a ``ComputeMethodInput`` key, captures the ambient
currently-computing node as the dependency edge source, and runs the
Read→Lock→RetryRead→Compute→Store pipeline (see function.py).
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Optional

from .context import OPT_INVALIDATE_BIT, CallOptions, ComputeContext, get_current
from .function import ComputeMethodFunction
from .hub import FusionHub, default_hub
from .inputs import ComputeMethodInput
from .options import ComputedOptions

__all__ = ["compute_method", "ComputeService", "ComputeMethodDef", "hub_of"]


class ComputeMethodDef:
    """Per-method metadata + per-(hub) function cache
    (≈ ComputeMethodDef, Interception/ComputeMethodDef.cs)."""

    __slots__ = ("original", "name", "options", "signature", "_functions")

    def __init__(self, original: Callable, options: ComputedOptions):
        self.original = original
        self.name = original.__qualname__
        self.options = options
        self.signature = inspect.signature(original)
        self._functions: dict = {}

    def get_function(self, service: Any) -> ComputeMethodFunction:
        hub = hub_of(service)
        fn = self._functions.get(id(hub))
        if fn is None:
            fn = ComputeMethodFunction(hub, self)
            self._functions[id(hub)] = fn
        return fn

    def bind_args(self, service: Any, args: tuple, kwargs: dict) -> tuple:
        """Normalize (args, kwargs) → canonical positional tuple so
        ``get(x=1)`` and ``get(1)`` share one cache slot."""
        if not kwargs:
            return args
        bound = self.signature.bind(service, *args, **kwargs)
        bound.apply_defaults()
        return tuple(bound.arguments.values())[1:]  # drop self


def hub_of(service: Any) -> FusionHub:
    hub = getattr(service, "_fusion_hub", None)
    return hub if hub is not None else default_hub()


def compute_method(
    fn: Optional[Callable] = None,
    *,
    min_cache_duration: Optional[float] = None,
    auto_invalidation_delay: Optional[float] = None,
    invalidation_delay: Optional[float] = None,
    transient_error_invalidation_delay: Optional[float] = None,
):
    """Decorator turning an async method into a memoized compute method.

    ≈ ``[ComputeMethod]`` (ComputeMethodAttribute.cs + ComputedOptions.cs
    resolution). Options map 1:1 onto ComputedOptions.
    """

    def decorate(func: Callable) -> Callable:
        if not inspect.iscoroutinefunction(func):
            raise TypeError(f"@compute_method requires an async def, got {func!r}")
        options = ComputedOptions.new(
            min_cache_duration=min_cache_duration,
            auto_invalidation_delay=auto_invalidation_delay,
            invalidation_delay=invalidation_delay,
            transient_error_invalidation_delay=transient_error_invalidation_delay,
        )
        method_def = ComputeMethodDef(func, options)

        @functools.wraps(func)
        async def wrapper(self, *args, **kwargs):
            function = method_def.get_function(self)
            input = ComputeMethodInput(
                method_def, self, method_def.bind_args(self, args, kwargs), function
            )
            context = ComputeContext.current()
            copts = context.call_options
            if copts == 0:
                # memoized-hit fast path (the reference's 50M-ops/sec READ,
                # Function.cs:56): default call mode + consistent node →
                # attach the edge and return without further awaits
                existing = function.hub.registry.get(input)
                if existing is not None and existing.is_consistent:
                    used_by = get_current()
                    if used_by is not None:
                        used_by.add_used(existing)
                    existing.renew_timeouts(False)
                    return existing.output.value
                return await function.invoke_and_strip(input, get_current(), context)
            # the ambient computing node is the dependency-capture root —
            # except inside an invalidation replay, where no edges form
            used_by = None if copts & OPT_INVALIDATE_BIT else get_current()
            return await function.invoke_and_strip(input, used_by, context)

        wrapper.__compute_method_def__ = method_def  # type: ignore[attr-defined]
        return wrapper

    if fn is not None:
        return decorate(fn)
    return decorate


class ComputeService:
    """Optional base for compute services: explicit hub binding + helpers.

    Any class works with @compute_method; inheriting this adds hub plumbing
    (≈ IComputeService marker)."""

    _fusion_hub: Optional[FusionHub] = None

    def __init__(self, hub: Optional[FusionHub] = None):
        self._fusion_hub = hub

    def _bind_hub(self, hub: FusionHub) -> None:
        self._fusion_hub = hub
