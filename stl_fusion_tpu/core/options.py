"""ComputedOptions — per-method caching/invalidation knobs.

Re-expression of src/Stl.Fusion/ComputedOptions.cs:5-66:
- ``min_cache_duration``: keep a strong reference to the node this long after
  each access (keep-alive timer), so it survives GC even with no dependents;
- ``auto_invalidation_delay``: invalidate automatically this long after each
  successful compute (the "time as a dependency" device, e.g. FusionTime);
- ``invalidation_delay``: debounce — an invalidate() call schedules the real
  invalidation after this delay instead of firing immediately;
- ``transient_error_invalidation_delay``: errors are memoized too, but only
  this long (default 1 s) so transient failures self-heal.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import ClassVar, Optional

__all__ = ["ComputedOptions"]

_INF = float("inf")


@dataclass(frozen=True)
class ComputedOptions:
    # The reference leaves MinCacheDuration=0 and relies on .NET's lazy GC to
    # keep hot nodes alive between accesses; CPython refcounting frees them
    # instantly, so a nonzero default keep-alive is required for memoization
    # to exist at all. Explicit 0 restores pure-weak semantics.
    min_cache_duration: float = 60.0
    auto_invalidation_delay: float = _INF  # inf = never
    invalidation_delay: float = 0.0
    transient_error_invalidation_delay: float = 1.0

    DEFAULT: ClassVar["ComputedOptions"]
    # Client-side default mirrors the reference's 1-minute ClientDefault
    # (ComputedOptions.cs:8-11)
    CLIENT_DEFAULT: ClassVar["ComputedOptions"]

    @property
    def has_auto_invalidation(self) -> bool:
        return self.auto_invalidation_delay != _INF

    @staticmethod
    def new(
        min_cache_duration: Optional[float] = None,
        auto_invalidation_delay: Optional[float] = None,
        invalidation_delay: Optional[float] = None,
        transient_error_invalidation_delay: Optional[float] = None,
        base: Optional["ComputedOptions"] = None,
    ) -> "ComputedOptions":
        b = base or ComputedOptions.DEFAULT
        return ComputedOptions(
            min_cache_duration if min_cache_duration is not None else b.min_cache_duration,
            auto_invalidation_delay if auto_invalidation_delay is not None else b.auto_invalidation_delay,
            invalidation_delay if invalidation_delay is not None else b.invalidation_delay,
            transient_error_invalidation_delay
            if transient_error_invalidation_delay is not None
            else b.transient_error_invalidation_delay,
        )


ComputedOptions.DEFAULT = ComputedOptions()
ComputedOptions.CLIENT_DEFAULT = ComputedOptions(min_cache_duration=60.0)
