"""Atomic operation scope — the exactly-once invalidation guarantee.

Re-expression of src/Stl.Fusion.EntityFramework/DbOperationScope.cs:25-130
(+ Operations/DbOperationScopeProvider.cs): ONE sqlite transaction owns both
the command's DAL writes and the operation record. The r1 design committed
them separately (the DAL autocommitted, the op log appended afterwards), so
a crash in between silently lost the invalidation record and other hosts
served stale values forever — VERDICT r1 "what's missing" #1. With the
scope:

- the scope opens ``BEGIN IMMEDIATE`` on the shared sqlite file;
- DAL handles built on :class:`ScopedSqliteDb` transparently enroll — their
  statements ride the scope's connection whenever a scope is ambient
  (≈ DbOperationScope enrolling every DbContext on the master connection);
- at success the operation row is inserted and the transaction commits
  ONCE — the op record and the business writes become durable atomically
  (op exists XOR writes absent is impossible);
- a failed commit is VERIFIED against a fresh connection: if the op row is
  durable the commit actually landed (the reference's commit-verification
  error path, DbOperationScope.cs error handling).

The scope provider installs as a commander filter between the transient
operation scope (which creates the Operation and drives completion) and the
nested-command logger — the reference's ordering
(FusionOperationsCommandHandlerPriority: DbOperationScopeProvider inside
TransientOperationScopeProvider).
"""
from __future__ import annotations

import contextvars
import logging
import os
import sqlite3
import time
from typing import TYPE_CHECKING, Any, Optional

from ..core.context import is_invalidating
from ..operations.operation import Completion, Operation
from .log import OperationRecord, ensure_operations_schema, insert_operation_row

if TYPE_CHECKING:
    from ..commands.commander import Commander
    from ..commands.context import CommandContext

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "SqliteOperationScope",
    "ScopedSqliteDb",
    "current_operation_scope",
    "attach_db_operation_scope",
]

#: priority slot between the transient scope provider (90) and the nested
#: command logger (80) — see operations/pipeline.py
PRIORITY_DB_SCOPE_PROVIDER = 85

_current_scope: contextvars.ContextVar[Optional["SqliteOperationScope"]] = (
    contextvars.ContextVar("fusion_db_operation_scope", default=None)
)


def current_operation_scope() -> Optional["SqliteOperationScope"]:
    """The ambient scope, if a command with DB operations is executing."""
    return _current_scope.get()


class SqliteOperationScope:
    """One transaction for one operation (≈ DbOperationScope.cs:25-130)."""

    def __init__(self, path: str, operation: Operation, ensure_schema: bool = True):
        # realpath: enrollment matches by path (ScopedSqliteDb.conn), so
        # './db' vs its absolute spelling must compare equal — a mismatch
        # would silently void the atomicity guarantee
        self.path = os.path.realpath(path)
        self.operation = operation
        self.committed = False
        self.closed = False
        self.conn = sqlite3.connect(self.path, timeout=30.0)
        if ensure_schema:
            # WAL: readers (other hosts' log tails) never block the writer
            self.conn.execute("PRAGMA journal_mode=WAL")
            ensure_operations_schema(self.conn)
            self.conn.commit()
        self.conn.execute("BEGIN IMMEDIATE")

    # -- lifecycle ---------------------------------------------------------
    def commit(self) -> None:
        """Write the operation row and commit EVERYTHING at once."""
        op = self.operation
        if op.commit_time is None:
            op.commit_time = time.time()
        insert_operation_row(
            self.conn,
            OperationRecord(
                id=op.id,
                agent_id=op.agent_id,
                commit_time=op.commit_time,
                command=op.command,
                items=tuple(op.items),
            ),
        )
        try:
            self.conn.commit()
        except Exception:
            # ambiguous failure: the commit may or may not have landed —
            # verify against a FRESH connection (reference commit
            # verification, DbOperationScope.cs error path)
            if not self.verify_committed():
                raise
            log.warning("operation %s: commit reported failure but is durable", op.id)
        self.committed = True

    def rollback(self) -> None:
        try:
            self.conn.rollback()
        except Exception:  # noqa: BLE001
            log.exception("operation %s rollback failed", self.operation.id)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.conn.close()

    def verify_committed(self) -> bool:
        """Is the operation row durable? (fresh connection, fresh snapshot)"""
        check = sqlite3.connect(self.path, timeout=30.0)
        try:
            row = check.execute(
                "SELECT 1 FROM operations WHERE id=?", (self.operation.id,)
            ).fetchone()
            return row is not None
        finally:
            check.close()


class ScopedSqliteDb:
    """A DAL connection handle that transparently enrolls in the ambient
    operation scope: inside a command, statements ride the scope's
    transaction (and the scope commits once, together with the op record);
    outside, a private autocommitting connection is used. The analogue of a
    DbContext created through DbHub inside DbOperationScope."""

    def __init__(self, path: str):
        self.path = os.path.realpath(path)
        self._own = sqlite3.connect(self.path, timeout=30.0)
        self._own.execute("PRAGMA journal_mode=WAL")
        self._own.commit()

    @property
    def conn(self) -> sqlite3.Connection:
        scope = _current_scope.get()
        if scope is not None and scope.path == self.path and not scope.closed:
            return scope.conn
        return self._own

    @property
    def in_scope(self) -> bool:
        scope = _current_scope.get()
        return scope is not None and scope.path == self.path and not scope.closed

    def execute(self, sql: str, params=()):
        return self.conn.execute(sql, params)

    def executescript(self, script: str):
        # DDL must not ride (and implicitly commit) an operation scope
        assert not self.in_scope, "run schema DDL outside command scopes"
        return self._own.executescript(script)

    def commit(self) -> None:
        """Commit ONLY when no scope is active — the scope owns the real
        commit, which is what makes the op record atomic with the writes."""
        if not self.in_scope:
            self._own.commit()

    def close(self) -> None:
        self._own.close()


def attach_db_operation_scope(commander: "Commander", db_path: str) -> None:
    """Install the scope-provider filter: every top-level mutating command
    gets ONE transaction spanning its DAL writes and its operation record
    (≈ DbOperationScopeProvider.cs)."""
    commander.attach_operations_pipeline()
    db_path = os.path.realpath(db_path)
    # schema + WAL are set up ONCE here, not per command
    setup = sqlite3.connect(db_path, timeout=30.0)
    setup.execute("PRAGMA journal_mode=WAL")
    ensure_operations_schema(setup)
    setup.commit()
    setup.close()

    async def db_operation_scope_provider(command: Any, context: "CommandContext"):
        operation = context.items.get(Operation)
        if (
            operation is None  # nested command: rides the outer scope
            or isinstance(command, Completion)
            or is_invalidating()
        ):
            return await context.invoke_remaining_handlers()
        scope = SqliteOperationScope(db_path, operation, ensure_schema=False)
        context.items.set(scope, key=SqliteOperationScope)
        token = _current_scope.set(scope)
        try:
            result = await context.invoke_remaining_handlers()
            scope.commit()
            return result
        except BaseException:
            if not scope.committed:
                scope.rollback()
            raise
        finally:
            _current_scope.reset(token)
            scope.close()

    commander.registry.add_function(
        db_operation_scope_provider,
        command_type=object,
        priority=PRIORITY_DB_SCOPE_PROVIDER,
        is_filter=True,
    )
