"""Operation-log reader + change notifiers — cross-host invalidation.

Re-expression of src/Stl.Fusion.EntityFramework/Operations/
DbOperationLogReader.cs:7-128 and the change-notifier family (Npgsql NOTIFY,
Redis pub/sub, file watcher — §2.6): each host runs a reader that tails the
shared log from a position watermark, filters out its OWN operations
(agent_id match, :85-92), and feeds external ones into the local
OperationCompletionNotifier — whose CompletionProducer →
PostCompletionInvalidator pipeline replays them as invalidations, exactly
like local completions.

Notifiers wake the reader without polling; the in-process ``LocalChangeNotifier``
is the test/fan-out default, ``FileChangeNotifier`` watches a touch-file
(≈ FileBasedDbOperationLogChangeNotifier) for cross-process setups.
"""
from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import TYPE_CHECKING, Callable, List, Optional

import contextlib

from ..operations.operation import Operation
from ..operations.pipeline import batch_cascade_scope
from ..utils.async_chain import WorkerBase
from .log import OperationLog, OperationRecord

if TYPE_CHECKING:
    from ..operations.pipeline import OperationsHost

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["OperationLogReader", "LocalChangeNotifier", "FileChangeNotifier", "attach_operation_log"]


class LocalChangeNotifier:
    """In-process wakeup fan-out (multi-"host" single-process tests)."""

    def __init__(self):
        self._events: List[asyncio.Event] = []

    def subscribe(self) -> asyncio.Event:
        ev = asyncio.Event()
        self._events.append(ev)
        return ev

    def notify(self) -> None:
        for ev in self._events:
            ev.set()


class FileChangeNotifier:
    """Touch-file wakeup for cross-process hosts sharing a log file."""

    def __init__(self, path: str):
        self.path = path
        self._local = LocalChangeNotifier()
        self._last_mtime = 0.0

    def subscribe(self) -> asyncio.Event:
        return self._local.subscribe()

    def notify(self) -> None:
        with open(self.path, "a") as f:
            f.write("")
        os.utime(self.path, None)
        self._local.notify()

    def poll(self) -> bool:
        try:
            m = os.path.getmtime(self.path)
        except OSError:
            return False
        if m > self._last_mtime:
            self._last_mtime = m
            self._local.notify()
            return True
        return False


class OperationLogReader(WorkerBase):
    def __init__(
        self,
        log_store: OperationLog,
        operations: "OperationsHost",
        notifier=None,
        poll_period: float = 0.25,
        start_from_end: bool = True,
        batch_size: int = 1024,
        start_position: Optional[int] = None,
        mesh=None,
    ):
        super().__init__("oplog-reader")
        self.log_store = log_store
        self.operations = operations
        self.notifier = notifier
        self.poll_period = poll_period
        self.batch_size = batch_size
        #: optional jax.sharding.Mesh: external-operation lane replay runs
        #: on the DEVICE MESH (invalidate_cascade_batch_lanes_sharded) — N
        #: external commands cost one packed mesh sweep over ICI
        self.mesh = mesh
        # explicit position (checkpoint resume) > tail-from-end > full replay
        if start_position is not None:
            self.watermark = start_position
        else:
            self.watermark = log_store.last_index() if start_from_end else 0
        self.external_seen = 0

    async def on_run(self) -> None:
        wake = self.notifier.subscribe() if self.notifier is not None else None
        # file-backed notifiers only learn about OTHER processes' commits by
        # polling the touch-file mtime, so they poll at poll_period; purely
        # local notifiers wake on the event and keep a 4x safety poll only
        pollable = hasattr(self.notifier, "poll")
        while True:
            await self.read_new()
            if wake is not None:
                timeout = self.poll_period if pollable else self.poll_period * 4
                try:
                    await asyncio.wait_for(wake.wait(), timeout)
                except asyncio.TimeoutError:
                    pass  # safety poll: progress even on missed notifications
                wake.clear()
                if pollable:
                    self.notifier.poll()
            else:
                await asyncio.sleep(self.poll_period)

    async def read_new(self) -> int:
        """Tail from the watermark; feed EXTERNAL operations to completion.

        When the hub has a TPU graph backend, a batch of external
        operations lane-packs: each operation's replay COLLECTS its
        directly-invalidated computeds (``invalidating(sink=...)``) as one
        group, and the whole batch cascades in one device lane burst
        (``invalidate_cascade_batch_lanes``) — the production consumer of
        the lane path: N external commands cost one mirror sweep, not N
        host cascades. Without a backend the replay cascades host-side per
        operation, exactly as before."""
        handled = 0
        backend = getattr(self.operations.commander.hub, "graph_backend", None)
        while True:
            records = self.log_store.read_after(self.watermark, self.batch_size)
            if not records:
                return handled
            groups: List[List] = []
            scope = (
                batch_cascade_scope(groups.append)
                if backend is not None
                else contextlib.nullcontext()
            )
            try:
                with scope:
                    for rec in records:
                        self.watermark = max(self.watermark, rec.index)
                        if rec.agent_id == self.operations.agent.id:
                            continue  # our own operation: already completed locally
                        self.external_seen += 1
                        operation = Operation(
                            command=rec.command,
                            agent_id=rec.agent_id,
                            id=rec.id,
                            commit_time=rec.commit_time,
                            items=list(rec.items),
                        )
                        await self.operations.notify_completed(operation, is_local=False)
                        handled += 1
            finally:
                # the watermark has already advanced past collected records —
                # a cancellation mid-batch (reader.stop()) must still apply
                # what was collected, or those operations' invalidations
                # would be lost forever (replay never revisits them)
                if groups and any(groups):
                    if self.mesh is not None:
                        backend.invalidate_cascade_batch_lanes_sharded(
                            groups, mesh=self.mesh
                        )
                    else:
                        backend.invalidate_cascade_batch_lanes(groups)


def attach_operation_log(
    commander,
    log_store: OperationLog,
    notifier=None,
    start_reader: bool = True,
    start_position: Optional[int] = None,
    mesh=None,
) -> OperationLogReader:
    """Wire a commander's operations pipeline to a durable log:
    - local completions append to the log (+ notify),
    - a reader replays external completions from other hosts
      (``mesh=`` routes the lane replay over the device mesh).
    """
    commander.attach_operations_pipeline()
    operations = commander.operations

    async def persist(operation) -> None:
        self_rec = OperationRecord(
            id=operation.id,
            agent_id=operation.agent_id,
            commit_time=operation.commit_time or time.time(),
            command=operation.command,
            items=tuple(operation.items),
        )
        log_store.append(self_rec)
        if notifier is not None:
            notifier.notify()

    operations.commit_listeners.append(persist)
    reader = OperationLogReader(
        log_store, operations, notifier, start_position=start_position, mesh=mesh
    )
    if start_reader:
        reader.start()
    return reader
