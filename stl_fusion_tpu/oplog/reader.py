"""Operation-log reader + change notifiers — cross-host invalidation.

Re-expression of src/Stl.Fusion.EntityFramework/Operations/
DbOperationLogReader.cs:7-128 and the change-notifier family (Npgsql NOTIFY,
Redis pub/sub, file watcher — §2.6): each host runs a reader that tails the
shared log from a position watermark, filters out its OWN operations
(agent_id match, :85-92), and feeds external ones into the local
OperationCompletionNotifier — whose CompletionProducer →
PostCompletionInvalidator pipeline replays them as invalidations, exactly
like local completions.

Notifiers wake the reader without polling; the in-process ``LocalChangeNotifier``
is the test/fan-out default, ``FileChangeNotifier`` watches a touch-file
(≈ FileBasedDbOperationLogChangeNotifier) for cross-process setups.
"""
from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import TYPE_CHECKING, Callable, List, Optional, Tuple

import contextlib

from dataclasses import dataclass

from ..diagnostics.flight_recorder import RECORDER
from ..diagnostics.tracing import get_activity_source
from ..operations.operation import Operation
from ..operations.pipeline import batch_cascade_scope
from ..resilience.events import ResilienceEvents, global_events
from ..utils.async_chain import WorkerBase
from .log import CorruptRecord, OperationLog, OperationRecord

if TYPE_CHECKING:
    from ..operations.pipeline import OperationsHost

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "OperationLogReader",
    "LocalChangeNotifier",
    "FileChangeNotifier",
    "QuarantinedRange",
    "attach_operation_log",
]


@dataclass(frozen=True)
class QuarantinedRange:
    """A log index range the reader skipped instead of halting on: a
    corrupt/truncated row, or a gap in the index sequence (rows that
    vanished mid-log — a torn write or external deletion). ``commit_floor``
    is the newest commit time known to be ≤ the range. ``clamps_trimmer``
    marks ranges with something left to PROTECT: a corrupt row is evidence
    a repaired cold boot can still replay, so the trimmer refuses to trim
    past it; a gap's rows are already gone (and commit-time/idx ordering
    skew can make a routine trim look like a mid-batch gap), so gaps are
    recorded as telemetry but never block GC."""

    first_index: int
    last_index: int
    commit_floor: Optional[float]
    reason: str
    clamps_trimmer: bool = True


class LocalChangeNotifier:
    """In-process wakeup fan-out (multi-"host" single-process tests)."""

    def __init__(self):
        self._events: List[asyncio.Event] = []

    def subscribe(self) -> asyncio.Event:
        ev = asyncio.Event()
        self._events.append(ev)
        return ev

    def notify(self) -> None:
        for ev in self._events:
            ev.set()


class FileChangeNotifier:
    """Touch-file wakeup for cross-process hosts sharing a log file."""

    def __init__(self, path: str):
        self.path = path
        self._local = LocalChangeNotifier()
        self._last_token: Tuple[float, int] = (0.0, -1)

    def subscribe(self) -> asyncio.Event:
        return self._local.subscribe()

    def notify(self) -> None:
        # the appended byte makes the file SIZE a shared monotonic token:
        # two notifies inside one clock tick (coarse-granularity filesystems
        # tick ~10ms here), or from two processes with skewed clocks, would
        # collide on mtime alone and silently drop a cross-process wakeup.
        # Growth is one byte per commit notification — negligible next to
        # the operation log it accompanies (and truncating the file is safe:
        # a size DECREASE also changes the token).
        with open(self.path, "a") as f:
            f.write(".")
        os.utime(self.path, None)
        self._local.notify()

    def poll(self) -> bool:
        try:
            st = os.stat(self.path)
        except OSError:
            return False
        token = (st.st_mtime, st.st_size)
        if token != self._last_token:
            self._last_token = token
            self._local.notify()
            return True
        return False


class OperationLogReader(WorkerBase):
    def __init__(
        self,
        log_store: OperationLog,
        operations: "OperationsHost",
        notifier=None,
        poll_period: float = 0.25,
        start_from_end: bool = True,
        batch_size: int = 1024,
        start_position: Optional[int] = None,
        mesh=None,
        events: Optional[ResilienceEvents] = None,
    ):
        super().__init__("oplog-reader")
        self.log_store = log_store
        self.operations = operations
        self.notifier = notifier
        self.poll_period = poll_period
        self.batch_size = batch_size
        self.events = events if events is not None else global_events()
        #: ranges skipped instead of halting on (corrupt rows, index gaps);
        #: the trimmer's quarantine guard reads quarantine_floor() off this
        self.quarantined: List[QuarantinedRange] = []
        self.corrupt_seen = 0
        self.gaps_seen = 0
        self._last_commit_time: Optional[float] = None
        #: optional jax.sharding.Mesh: external-operation lane replay runs
        #: on the DEVICE MESH (invalidate_cascade_batch_lanes_sharded) — N
        #: external commands cost one packed mesh sweep over ICI
        self.mesh = mesh
        # explicit position (checkpoint resume) > tail-from-end > full replay
        if start_position is not None:
            self.watermark = start_position
        else:
            self.watermark = log_store.last_index() if start_from_end else 0
        self.external_seen = 0
        # reader-lag gauge for /metrics (ISSUE 3): how far this reader's
        # watermark trails the writer's last index — THE cross-host
        # staleness number. Weak-registered; a dead reader drops out.
        from ..diagnostics.metrics import global_metrics

        global_metrics().register_collector(self, OperationLogReader._collect_metrics)
        # non-additive: the WORST reader's lag, never the sum over readers
        global_metrics().set_aggregation("fusion_oplog_reader_lag", "max")

    def _collect_metrics(self) -> dict:
        try:
            lag = max(self.log_store.last_index() - self.watermark, 0)
        except Exception:  # noqa: BLE001 — a failing store must not kill a scrape
            lag = -1
        return {
            "fusion_oplog_reader_lag": lag,
            "fusion_oplog_external_seen_total": self.external_seen,
            "fusion_oplog_corrupt_seen_total": self.corrupt_seen,
            "fusion_oplog_gaps_seen_total": self.gaps_seen,
        }

    async def on_run(self) -> None:
        wake = self.notifier.subscribe() if self.notifier is not None else None
        # file-backed notifiers only learn about OTHER processes' commits by
        # polling the touch-file mtime, so they poll at poll_period; purely
        # local notifiers wake on the event and keep a 4x safety poll only
        pollable = hasattr(self.notifier, "poll")
        while True:
            await self.read_new()
            if wake is not None:
                timeout = self.poll_period if pollable else self.poll_period * 4
                try:
                    await asyncio.wait_for(wake.wait(), timeout)
                except asyncio.TimeoutError:
                    pass  # safety poll: progress even on missed notifications
                wake.clear()
                if pollable:
                    self.notifier.poll()
            else:
                await asyncio.sleep(self.poll_period)

    async def read_new(self) -> int:
        """Tail from the watermark; feed EXTERNAL operations to completion.

        When the hub has a TPU graph backend, a batch of external
        operations lane-packs: each operation's replay COLLECTS its
        directly-invalidated computeds (``invalidating(sink=...)``) as one
        group, and the whole batch cascades in one device lane burst
        (``invalidate_cascade_batch_lanes``) — the production consumer of
        the lane path: N external commands cost one mirror sweep, not N
        host cascades. Without a backend the replay cascades host-side per
        operation, exactly as before."""
        handled = 0
        backend = getattr(self.operations.commander.hub, "graph_backend", None)
        while True:
            records = self.log_store.read_after(self.watermark, self.batch_size)
            if not records:
                return handled
            groups: List[List] = []
            scope = (
                batch_cascade_scope(groups.append)
                if backend is not None
                else contextlib.nullcontext()
            )
            # a gap is only trustworthy INSIDE one read batch (the store
            # returned rows on both sides of a hole in ONE query): rows
            # missing ACROSS batches — or before the first record — may have
            # been legitimately trimmed while this reader lagged, and a
            # false gap would clamp the trimmer at its commit floor forever
            prev_index: Optional[int] = None
            try:
                with scope:
                    for rec in records:
                        if prev_index is not None and rec.index > prev_index + 1:
                            self.gaps_seen += 1
                            self._quarantine(
                                prev_index + 1, rec.index - 1,
                                self._last_commit_time,
                                "index gap", "oplog_gap",
                                clamps_trimmer=False,
                            )
                        prev_index = rec.index
                        self.watermark = max(self.watermark, rec.index)
                        if isinstance(rec, CorruptRecord):
                            # torn/garbled row: quarantine + RESUME at the
                            # next good watermark instead of halting the
                            # whole invalidation fan-out on one bad write
                            self._quarantine(
                                rec.index, rec.index,
                                rec.commit_time or self._last_commit_time,
                                f"corrupt: {rec.error}", "oplog_corrupt",
                            )
                            self.corrupt_seen += 1
                            continue
                        self._last_commit_time = rec.commit_time
                        if rec.agent_id == self.operations.agent.id:
                            continue  # our own operation: already completed locally
                        self.external_seen += 1
                        operation = Operation(
                            command=rec.command,
                            agent_id=rec.agent_id,
                            id=rec.id,
                            commit_time=rec.commit_time,
                            items=list(rec.items),
                            cause_id=rec.cause,
                        )
                        if rec.cause:
                            # cross-host command attribution (ISSUE 20): the
                            # origin member journaled the command span's
                            # cause id; teaching the local trace store the
                            # label lets stitch()/explain() on THIS host
                            # name the originating command too
                            from ..diagnostics.mesh_telemetry import global_mesh_trace

                            global_mesh_trace().note_command(
                                rec.cause,
                                f"{type(rec.command).__name__} "
                                f"(op {rec.id[:8]}, agent {rec.agent_id})",
                            )
                        if RECORDER.enabled:
                            # the flight-journal join point for cross-host
                            # causality: explain() resolves "via oplog entry
                            # E on host H" from these
                            RECORDER.note(
                                "oplog_replayed",
                                key=f"oplog:{rec.agent_id}",
                                oplog=rec.index,
                                detail=type(rec.command).__name__,
                            )
                        # replay under a span: host-led invalidations this
                        # completion cascades stamp a cause id naming the
                        # originating oplog record (computed.py stamps from
                        # the open span); recorder events auto-carry the
                        # index via current_oplog
                        prev_oplog = RECORDER.current_oplog
                        RECORDER.current_oplog = rec.index
                        try:
                            with get_activity_source("oplog").span(
                                "replay", index=rec.index, agent=rec.agent_id
                            ):
                                await self.operations.notify_completed(
                                    operation, is_local=False
                                )
                        finally:
                            RECORDER.current_oplog = prev_oplog
                        handled += 1
            finally:
                # the watermark has already advanced past collected records —
                # a cancellation mid-batch (reader.stop()) must still apply
                # what was collected, or those operations' invalidations
                # would be lost forever (replay never revisits them)
                if groups and any(groups):
                    # the burst covers every record of this batch; the span
                    # names the range so lane-wave causes resolve to it
                    with get_activity_source("oplog").span(
                        "batch", upto=self.watermark, groups=len(groups)
                    ):
                        if self.mesh is not None:
                            backend.invalidate_cascade_batch_lanes_sharded(
                                groups, mesh=self.mesh
                            )
                        else:
                            backend.invalidate_cascade_batch_lanes(groups)

    # ------------------------------------------------------------------ quarantine
    def _quarantine(
        self,
        first: int,
        last: int,
        commit_floor: Optional[float],
        reason: str,
        kind: str,
        clamps_trimmer: bool = True,
    ) -> None:
        rng = QuarantinedRange(first, last, commit_floor, reason, clamps_trimmer)
        self.quarantined.append(rng)
        self.events.record(kind, f"[{first}, {last}] {reason}")
        log.warning("oplog reader quarantined [%d, %d]: %s", first, last, reason)

    def quarantine_floor(self) -> Optional[float]:
        """Oldest commit time the trimmer must PRESERVE: the minimum commit
        floor across trimmer-clamping quarantined ranges (None when nothing
        clamps; 0.0 — trim nothing — when a clamping range couldn't be
        dated). Gap ranges never clamp: their rows are already gone, and a
        false gap (trim vs commit-time/idx skew) must not disable GC."""
        floors = [r.commit_floor for r in self.quarantined if r.clamps_trimmer]
        if not floors:
            return None
        return 0.0 if any(f is None for f in floors) else min(floors)

    def clear_quarantine(self) -> int:
        """Operator reset after inspecting (or repairing) quarantined rows:
        forget the ranges so the trimmer resumes normal GC. Returns the
        number of ranges dropped."""
        n = len(self.quarantined)
        self.quarantined.clear()
        return n


def attach_operation_log(
    commander,
    log_store: OperationLog,
    notifier=None,
    start_reader: bool = True,
    start_position: Optional[int] = None,
    mesh=None,
) -> OperationLogReader:
    """Wire a commander's operations pipeline to a durable log:
    - local completions append to the log (+ notify),
    - a reader replays external completions from other hosts
      (``mesh=`` routes the lane replay over the device mesh).
    """
    commander.attach_operations_pipeline()
    operations = commander.operations

    async def persist(operation) -> None:
        self_rec = OperationRecord(
            id=operation.id,
            agent_id=operation.agent_id,
            commit_time=operation.commit_time or time.time(),
            command=operation.command,
            items=tuple(operation.items),
            cause=getattr(operation, "cause_id", None),
        )
        log_store.append(self_rec)
        if notifier is not None:
            notifier.notify()

    operations.commit_listeners.append(persist)
    reader = OperationLogReader(
        log_store, operations, notifier, start_position=start_position, mesh=mesh
    )
    if start_reader:
        reader.start()
    return reader
