"""Durable operation log + multi-host invalidation (SURVEY.md §2.6)."""
from .entity_resolver import EntityResolver
from .log import (
    CorruptRecord,
    InMemoryOperationLog,
    OperationLog,
    OperationRecord,
    SqliteOperationLog,
)
from .trimmer import OperationLogTrimmer
from .scope import (
    ScopedSqliteDb,
    SqliteOperationScope,
    attach_db_operation_scope,
    current_operation_scope,
)
from .reader import (
    FileChangeNotifier,
    LocalChangeNotifier,
    OperationLogReader,
    QuarantinedRange,
    attach_operation_log,
)

__all__ = [
    "CorruptRecord",
    "EntityResolver",
    "InMemoryOperationLog",
    "OperationLog",
    "OperationRecord",
    "SqliteOperationLog",
    "FileChangeNotifier",
    "LocalChangeNotifier",
    "OperationLogReader",
    "OperationLogTrimmer",
    "QuarantinedRange",
    "attach_operation_log",
    "ScopedSqliteDb",
    "SqliteOperationScope",
    "attach_db_operation_scope",
    "current_operation_scope",
]
