"""Durable operation log + multi-host invalidation (SURVEY.md §2.6)."""
from .log import InMemoryOperationLog, OperationLog, OperationRecord, SqliteOperationLog
from .reader import (
    FileChangeNotifier,
    LocalChangeNotifier,
    OperationLogReader,
    attach_operation_log,
)

__all__ = [
    "InMemoryOperationLog",
    "OperationLog",
    "OperationRecord",
    "SqliteOperationLog",
    "FileChangeNotifier",
    "LocalChangeNotifier",
    "OperationLogReader",
    "attach_operation_log",
]
