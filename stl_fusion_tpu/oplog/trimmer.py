"""OperationLogTrimmer — background op-log GC.

Re-expression of src/Stl.Fusion.EntityFramework/Operations/
DbOperationLogTrimmer.cs: a periodic worker that drops operation records
older than ``max_age`` so the durable log stays bounded. Readers keep
commit-time watermarks (reader.py), so trimming behind every host's
watermark is safe; ``max_age`` should exceed the reader's max commit age.

Two guards clamp the cutoff, and the trim respects the MIN of both:

- ``quarantine_guard`` (PR 1) — never trim past a quarantined corrupt row
  (the evidence must outlive GC).
- ``snapshot_guard`` (ISSUE 6) — never trim the replay tail above a
  retained snapshot's watermark: a warm rejoin restores the snapshot and
  replays exactly the entries above it; trimming them strands the member
  with a permanently stale warm state. Anything exposing
  ``snapshot_floor() -> Optional[float]`` fits (CheckpointManager does).
"""
from __future__ import annotations

import logging
import time
from typing import Optional

from ..utils.async_chain import WorkerBase
from ..utils.moment import MomentClock
from .log import OperationLog

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["OperationLogTrimmer"]


class OperationLogTrimmer(WorkerBase):
    def __init__(
        self,
        log_store: OperationLog,
        max_age: float = 600.0,
        check_period: float = 60.0,
        clock: Optional[MomentClock] = None,
        quarantine_guard=None,
        snapshot_guard=None,
    ):
        super().__init__(name="oplog-trimmer")
        self.log_store = log_store
        self.max_age = max_age
        self.check_period = check_period
        self.clock = clock
        #: an OperationLogReader (or anything with ``quarantine_floor() ->
        #: Optional[float]``): the trimmer never trims past a quarantined
        #: range — the evidence of a torn/corrupt row must outlive the GC
        #: so operators can inspect it and cold-boot readers can replay a
        #: repaired row
        self.quarantine_guard = quarantine_guard
        #: a CheckpointManager (or anything with ``snapshot_floor() ->
        #: Optional[float]``): the trimmer never trims the replay tail a
        #: retained snapshot's warm rejoin still needs
        self.snapshot_guard = snapshot_guard
        self.trimmed_total = 0
        self.clamped_trims = 0
        self.snapshot_clamped_trims = 0

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else time.time()

    def trim_once(self) -> int:
        cutoff = self._now() - self.max_age
        if self.quarantine_guard is not None:
            floor = self.quarantine_guard.quarantine_floor()
            if floor is not None and floor < cutoff:
                cutoff = floor
                self.clamped_trims += 1
        if self.snapshot_guard is not None:
            floor = self.snapshot_guard.snapshot_floor()
            if floor is not None and floor < cutoff:
                cutoff = floor
                self.snapshot_clamped_trims += 1
        removed = self.log_store.trim_before(cutoff)
        self.trimmed_total += removed
        if removed:
            log.debug("oplog trimmer removed %d records", removed)
        return removed

    async def on_run(self) -> None:
        import asyncio

        while True:
            self.trim_once()
            if self.clock is not None:
                await self.clock.delay(self.check_period)  # TestClock-drivable
            else:
                await asyncio.sleep(self.check_period)
