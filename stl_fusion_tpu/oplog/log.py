"""Durable operation log — the multi-host invalidation backbone.

Re-expression of src/Stl.Fusion.EntityFramework/Operations/ (DbOperation,
IDbOperationLog, DbOperationScope): every completed command is appended as a
durable record (id, agent, commit time, serialized command + nested items);
other hosts tail the log and replay external operations as invalidations
(reader.py). Store-agnostic per SURVEY §7 step 7: a sqlite implementation
(stdlib — the durable default) and an in-memory one for tests.

This is also the checkpoint/resume story (SURVEY §5.4): a restarted host
re-reads from its commit-time watermark, so invalidation truth survives
restarts.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, List, Optional

from ..utils.serialization import decode, encode

__all__ = [
    "CorruptRecord",
    "OperationRecord",
    "OperationLog",
    "SqliteOperationLog",
    "InMemoryOperationLog",
    "ensure_operations_schema",
    "insert_operation_row",
]


def ensure_operations_schema(conn: sqlite3.Connection) -> None:
    """Create the operations table (shared between SqliteOperationLog and
    the atomic SqliteOperationScope, which writes the row inside the SAME
    transaction as the command's DAL writes — oplog/scope.py)."""
    conn.execute(
        """CREATE TABLE IF NOT EXISTS operations (
            idx INTEGER PRIMARY KEY AUTOINCREMENT,
            id TEXT UNIQUE,
            agent_id TEXT,
            commit_time REAL,
            command_json TEXT,
            items_json TEXT,
            cause_id TEXT
        )"""
    )
    # pre-ISSUE-20 databases lack the cause column; migrate in place (the
    # column is nullable, so old rows read back with cause=None)
    cols = {row[1] for row in conn.execute("PRAGMA table_info(operations)")}
    if "cause_id" not in cols:
        conn.execute("ALTER TABLE operations ADD COLUMN cause_id TEXT")
    conn.execute(
        "CREATE INDEX IF NOT EXISTS ix_operations_commit ON operations(commit_time)"
    )


def insert_operation_row(conn: sqlite3.Connection, record: "OperationRecord"):
    """INSERT the record (id-deduped) WITHOUT committing — the caller owns
    the transaction."""
    return conn.execute(
        "INSERT OR IGNORE INTO operations"
        " (id, agent_id, commit_time, command_json, items_json, cause_id)"
        " VALUES (?, ?, ?, ?, ?, ?)",
        (
            record.id,
            record.agent_id,
            record.commit_time,
            json.dumps(encode(record.command)),
            json.dumps(encode(list(record.items))),
            record.cause,
        ),
    )


@dataclass(frozen=True)
class OperationRecord:
    id: str
    agent_id: str
    commit_time: float
    command: Any
    items: tuple  # nested commands
    index: int = 0  # log position (store-assigned)
    #: originating span/wave cause id (ISSUE 20): rides the log BOTH
    #: directions so a remote replay's stitched wave timeline attributes
    #: back to the command that minted the operation
    cause: Optional[str] = None


@dataclass(frozen=True)
class CorruptRecord:
    """A log row that exists but cannot be decoded (truncated/garbled
    payload — a torn write, a partial disk, a bad migration). Stores
    surface these instead of RAISING from ``read_after``: one poisoned row
    must not halt every reader forever (reader.py quarantines it and
    resumes at the next good watermark). ``commit_time`` is kept when the
    column itself survived — the trimmer uses it to never trim past a
    quarantined range."""

    index: int
    commit_time: Optional[float]
    error: str


class OperationLog:
    """Abstract durable operation log."""

    def append(self, record: OperationRecord) -> OperationRecord:
        raise NotImplementedError

    def read_after(self, index: int, limit: int = 1024) -> List[OperationRecord]:
        """Records with position > index, oldest first. A row that exists
        but cannot be decoded comes back as a :class:`CorruptRecord` in
        position — never an exception (reader.py quarantines and
        resumes)."""
        raise NotImplementedError

    def last_index(self) -> int:
        raise NotImplementedError

    def contains(self, operation_id: str) -> bool:
        """Is an operation with this id already journaled? The cluster
        commander's replay dedup (ISSUE 20): a retried command whose first
        attempt committed must NOT re-apply."""
        raise NotImplementedError

    def trim_before(self, commit_time: float) -> int:
        """Drop old records (≈ DbOperationLogTrimmer). Returns removed count."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryOperationLog(OperationLog):
    def __init__(self):
        self._records: List[OperationRecord] = []
        self._ids: dict = {}  # operation id -> record (the INSERT OR IGNORE analog)
        self._lock = threading.Lock()

    def append(self, record: OperationRecord) -> OperationRecord:
        with self._lock:
            # id-dedup mirrors the sqlite INSERT OR IGNORE: a replayed
            # operation (same id) journals once, never twice
            existing = self._ids.get(record.id)
            if existing is not None:
                return existing
            rec = OperationRecord(
                record.id, record.agent_id, record.commit_time, record.command,
                record.items, index=len(self._records) + 1, cause=record.cause,
            )
            self._records.append(rec)
            self._ids[rec.id] = rec
            return rec

    def read_after(self, index: int, limit: int = 1024) -> List[OperationRecord]:
        with self._lock:
            return [r for r in self._records if r.index > index][:limit]

    def last_index(self) -> int:
        with self._lock:
            return self._records[-1].index if self._records else 0

    def contains(self, operation_id: str) -> bool:
        with self._lock:
            return operation_id in self._ids

    def trim_before(self, commit_time: float) -> int:
        with self._lock:
            keep = [r for r in self._records if r.commit_time >= commit_time]
            removed = len(self._records) - len(keep)
            self._records = keep
            self._ids = {r.id: r for r in keep}
            return removed


class SqliteOperationLog(OperationLog):
    """Durable log in sqlite — the shared-DB pattern the reference's
    multi-host samples run on (two hosts, one database file)."""

    def __init__(
        self,
        path: str,
        busy_timeout_s: float = 30.0,
        synchronous: Optional[str] = None,
    ):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(
            path, check_same_thread=False, timeout=busy_timeout_s
        )
        if synchronous is None:
            synchronous = os.environ.get("FUSION_OPLOG_SYNC", "NORMAL")
        if synchronous.upper() not in ("OFF", "NORMAL", "FULL", "EXTRA"):
            raise ValueError(f"invalid synchronous level: {synchronous!r}")
        # WAL lets a snapshotting READER (another process/instance tailing
        # or checkpointing this log) run concurrently with an appending
        # WRITER — under the default rollback journal the reader takes a
        # shared lock that makes a loaded writer throw `database is
        # locked`. busy_timeout is the in-engine wait (sqlite3's `timeout`
        # arg only covers the connection-level retry loop; the pragma also
        # guards statements issued after the connection was handed out).
        # Both are best-effort: ":memory:" and some network filesystems
        # refuse WAL, and the log still works in rollback mode there.
        self.journal_mode = None
        try:
            self._conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_s * 1000)}")
            row = self._conn.execute("PRAGMA journal_mode=WAL").fetchone()
            self.journal_mode = row[0] if row else None
            # NORMAL removes the per-commit fsync stall that made append the
            # fan-out bottleneck under load, but a power loss can drop acked
            # rows from an unsynced WAL — which breaks the warm-rejoin
            # contract that snapshot watermark + surviving tail covers every
            # committed write. Deployments relying on exact-tail replay
            # across power loss should run FULL (constructor arg or
            # FUSION_OPLOG_SYNC=FULL); see DURABILITY.md "Trim safety".
            self._conn.execute(f"PRAGMA synchronous={synchronous.upper()}")
        except sqlite3.Error:  # pragma unsupported: keep default journaling
            pass
        ensure_operations_schema(self._conn)
        self._conn.commit()

    def append(self, record: OperationRecord) -> OperationRecord:
        with self._lock:
            cur = insert_operation_row(self._conn, record)
            self._conn.commit()
            idx = cur.lastrowid or 0
            return OperationRecord(
                record.id, record.agent_id, record.commit_time, record.command,
                record.items, index=idx, cause=record.cause,
            )

    def read_after(self, index: int, limit: int = 1024) -> List[OperationRecord]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT idx, id, agent_id, commit_time, command_json, items_json,"
                " cause_id FROM operations WHERE idx > ? ORDER BY idx LIMIT ?",
                (index, limit),
            ).fetchall()
        out: List[OperationRecord] = []
        for r in rows:
            try:
                out.append(
                    OperationRecord(
                        id=r[1],
                        agent_id=r[2],
                        commit_time=r[3],
                        command=decode(json.loads(r[4])),
                        items=tuple(decode(json.loads(r[5]))),
                        index=r[0],
                        cause=r[6],
                    )
                )
            except Exception as e:  # noqa: BLE001 — torn/garbled row: surface,
                # don't raise (one poisoned row must not halt every reader)
                commit_time = r[3] if isinstance(r[3], (int, float)) else None
                out.append(CorruptRecord(index=r[0], commit_time=commit_time, error=repr(e)))
        return out

    def last_index(self) -> int:
        with self._lock:
            row = self._conn.execute("SELECT MAX(idx) FROM operations").fetchone()
            return row[0] or 0

    def contains(self, operation_id: str) -> bool:
        with self._lock:
            row = self._conn.execute(
                "SELECT 1 FROM operations WHERE id = ? LIMIT 1", (operation_id,)
            ).fetchone()
            return row is not None

    def trim_before(self, commit_time: float) -> int:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM operations WHERE commit_time < ?", (commit_time,)
            )
            self._conn.commit()
            return cur.rowcount

    def close(self) -> None:
        self._conn.close()
