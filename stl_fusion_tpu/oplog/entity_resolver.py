"""EntityResolver — batched entity loads.

Re-expression of src/Stl.Fusion.EntityFramework/DbEntityResolver.cs: when
many concurrent compute methods each resolve one entity by key, the resolver
coalesces them into one batched backend query per event-loop tick (the
reference batches via a background processor with a batch-size cap).

``resolve(key)`` returns the entity or None; concurrent calls for the same
key share one backend fetch. The backend is any async callable
``fetch_many(keys) -> {key: entity}`` — a DB query, an RPC, a shard lookup.
"""
from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, Generic, Hashable, List, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

__all__ = ["EntityResolver"]


class EntityResolver(Generic[K, V]):
    def __init__(
        self,
        fetch_many: Callable[[List[K]], Awaitable[Dict[K, V]]],
        max_batch_size: int = 256,
    ):
        self._fetch_many = fetch_many
        self.max_batch_size = max_batch_size
        self._pending: Dict[K, "asyncio.Future[Optional[V]]"] = {}
        self._flush_scheduled = False
        #: in-flight flush tasks, retained (FL003): the loop holds tasks
        #: weakly and a collected flush would strand every batched waiter
        self._flush_tasks: set = set()
        self.batches = 0  # stats: backend round trips
        self.requests = 0

    async def resolve(self, key: K) -> Optional[V]:
        self.requests += 1
        fut = self._pending.get(key)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._pending[key] = fut
            if not self._flush_scheduled:
                self._flush_scheduled = True
                # flush on the next tick so same-tick callers join the batch
                asyncio.get_running_loop().call_soon(self._spawn_flush)
        return await asyncio.shield(fut)

    async def resolve_many(self, keys: List[K]) -> Dict[K, Optional[V]]:
        results = await asyncio.gather(*(self.resolve(k) for k in keys))
        return dict(zip(keys, results))

    def _spawn_flush(self) -> None:
        self._flush_scheduled = False
        if self._pending:
            task = asyncio.ensure_future(self._flush())
            self._flush_tasks.add(task)
            task.add_done_callback(self._flush_tasks.discard)

    async def _flush(self) -> None:
        while self._pending:
            batch_keys = list(self._pending.keys())[: self.max_batch_size]
            waiters = {k: self._pending.pop(k) for k in batch_keys}
            self.batches += 1
            try:
                found = await self._fetch_many(batch_keys)
            except Exception as e:  # noqa: BLE001 — propagate to every waiter
                for fut in waiters.values():
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for k, fut in waiters.items():
                if not fut.done():
                    fut.set_result(found.get(k))
