"""Testing utilities — the analogue of src/Stl.Testing/.

The reference ships a test toolkit its own suites build on: ``TestWebHost``
(in-proc Kestrel host wiring server+client DI containers,
Testing/TestWebHost.cs), ``TestClock`` (Time/Testing/), build-agent
detection (TestRunnerInfo.cs), and jittered time helpers
(src/Stl/Time/RandomTimeSpan.cs). This module re-expresses them for the
TPU build: a :class:`TestWebHost` that composes a full in-process fusion
stack (FusionHub + RpcHub + real websocket server) and hands out connected
invalidation-aware clients, plus the small time/env helpers.

The in-memory channel-pair transport (``rpc.testing.RpcTestTransport``) and
``TestClock`` re-export here so one import serves a test module.
"""
from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..client import compute_client, install_compute_call_type
from ..core.hub import FusionHub
from ..rpc.hub import RpcHub
from ..rpc.testing import RpcTestTransport
from ..utils.moment import TestClock

__all__ = [
    "TestWebHost",
    "RandomTimeSpan",
    "RpcTestTransport",
    "TestClock",
    "is_build_agent",
]


@dataclass(frozen=True)
class RandomTimeSpan:
    """Jittered duration: ``origin ± max_delta`` seconds, uniformly
    (src/Stl/Time/RandomTimeSpan.cs — used for staggered worker start
    delays so multi-host workers don't thundering-herd the op log)."""

    origin: float
    max_delta: float = 0.0

    def next(self, rng: Optional[random.Random] = None) -> float:
        if self.max_delta <= 0:
            return self.origin
        r = (rng or random).uniform(-self.max_delta, self.max_delta)
        return max(0.0, self.origin + r)

    @property
    def min(self) -> float:
        return max(0.0, self.origin - self.max_delta)

    @property
    def max(self) -> float:
        return self.origin + self.max_delta


def is_build_agent() -> bool:
    """CI detection (≈ TestRunnerInfo.IsBuildAgent) — suites relax
    timing-sensitive assertions on shared runners."""
    return any(os.environ.get(k) for k in ("CI", "GITHUB_ACTIONS", "BUILD_ID", "TF_BUILD"))


class TestWebHost:
    """A full in-process fusion host over a REAL websocket listener.

    ≈ src/Stl.Testing/TestWebHost.cs + the RpcTestBase pattern
    (tests/Stl.Tests/RpcTestBase.cs:28-70): the server side gets its own
    FusionHub + RpcHub bound to an ephemeral-port websocket server; each
    ``new_client`` call builds an isolated client container (own FusionHub +
    RpcHub) connected through the socket, so tests exercise the true
    serialize → socket → deserialize → invalidation-push path.

        async with TestWebHost() as host:
            host.add_service("counters", CounterService(host.fusion))
            client = await host.new_client("counters")
            await client.get("a")

    For protocol tests that need scripted disconnects, use
    ``RpcTestTransport`` directly instead (channel pair, no sockets).
    """

    __test__ = False  # pytest: not a test class despite the Test* name

    def __init__(self, use_http_gateway: bool = False):
        self.fusion = FusionHub()
        self.rpc = RpcHub("test-server")
        install_compute_call_type(self.rpc)
        self.use_http_gateway = use_http_gateway
        self.ws_server = None
        self.http_server = None
        self._client_rpc_hubs: List[RpcHub] = []
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "TestWebHost":
        from ..rpc.websocket import RpcWebSocketServer

        self.ws_server = await RpcWebSocketServer(self.rpc).start()
        if self.use_http_gateway:
            from ..rpc.http_gateway import FusionHttpServer

            self.http_server = await FusionHttpServer(self.rpc).start()
        self._started = True
        return self

    async def stop(self) -> None:
        for hub in self._client_rpc_hubs:
            await hub.stop()
        self._client_rpc_hubs.clear()
        await self.rpc.stop()
        if self.ws_server is not None:
            await self.ws_server.stop()
        if self.http_server is not None:
            await self.http_server.stop()
        self._started = False

    async def __aenter__(self) -> "TestWebHost":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    # -- server side -------------------------------------------------------
    def add_service(self, name: str, service: Any) -> Any:
        """Register a compute service on the host's RPC surface."""
        self.rpc.add_service(name, service)
        return service

    @property
    def url(self) -> str:
        assert self.ws_server is not None, "host not started"
        return self.ws_server.url

    @property
    def http_url(self) -> str:
        assert self.http_server is not None, "host not started with use_http_gateway"
        return self.http_server.url

    # -- client side -------------------------------------------------------
    def new_client_container(self, client_id: Optional[str] = None) -> tuple:
        """A fresh (FusionHub, RpcHub) pair wired to this host's socket —
        the separate client DI container of RpcTestBase."""
        from ..rpc.websocket import websocket_client_connector

        assert self._started, "host not started"
        client_fusion = FusionHub()
        client_rpc = RpcHub(f"test-client-{len(self._client_rpc_hubs)}")
        install_compute_call_type(client_rpc)
        client_rpc.client_connector = websocket_client_connector(self.url, client_id)
        self._client_rpc_hubs.append(client_rpc)
        return client_fusion, client_rpc

    async def new_client(self, service_name: str, cache=None, client_id: Optional[str] = None):
        """A connected invalidation-aware compute client for ``service_name``."""
        client_fusion, client_rpc = self.new_client_container(client_id)
        return compute_client(service_name, client_rpc, client_fusion, cache=cache)
