"""AuthStateProvider — live authentication state for UI surfaces.

Re-expression of src/Stl.Fusion.Blazor.Authentication/AuthStateProvider.cs
(+ AuthState.cs): a ComputedState over ``(auth.get_user(session),
auth.is_sign_out_forced(session))`` whose updates notify the UI — so a
sign-in/out ANYWHERE (this process, another host via the op log, a cookie
page-load reconciled by ServerAuthHelper) re-renders every component that
watches it. Where the reference plugs into Blazor's
``AuthenticationStateProvider`` cascade, here components either await
``use()`` inside their own ``compute_state`` (the dependency edge makes
them recompute on auth changes — the CascadingAuthState analogue) or
subscribe to ``changed_handlers``.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from ..core.hub import FusionHub
from ..state.computed_state import ComputedState

__all__ = ["AuthState", "AuthStateProvider"]


@dataclasses.dataclass(frozen=True)
class AuthState:
    """≈ AuthState.cs: the user (None = anonymous) + whether the session
    was force-closed (drives the 'you were signed out' UX)."""

    user: Optional[object] = None
    is_sign_out_forced: bool = False

    @property
    def is_authenticated(self) -> bool:
        return self.user is not None


class AuthStateProvider:
    def __init__(self, auth, session, hub: Optional[FusionHub] = None):
        self.auth = auth
        self.session = session
        self.changed_handlers: List[Callable[[AuthState], None]] = []
        self.state: ComputedState = ComputedState(
            self._compute, hub, name=f"auth-state:{session.id[:8]}"
        )
        self.state.updated_handlers.append(self._on_updated)
        self.state.start()

    async def _compute(self) -> AuthState:
        user = await self.auth.get_user(self.session)
        forced = await self.auth.is_sign_out_forced(self.session)
        return AuthState(user, forced)

    def _on_updated(self, state) -> None:
        out = state.snapshot.computed._output
        if out is None or out.has_error:
            return
        for handler in self.changed_handlers:
            handler(out.value)

    async def use(self) -> AuthState:
        """Read the auth state INSIDE a compute (a LiveComponent's
        ``compute_state``): the ambient node gains a dependency edge and
        recomputes whenever the auth state changes — the
        CascadingAuthState pattern."""
        return await self.state.use()

    async def get(self) -> AuthState:
        await self.state.update()
        return self.state.value

    async def dispose(self) -> None:
        await self.state.dispose()
