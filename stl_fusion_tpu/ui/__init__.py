"""UI layer (SURVEY.md §2.7): live components + action tracking."""
from .action_tracker import UIActionFailureTracker, UIActionTracker, UICommander
from .live_component import LiveComponent, MixedStateComponent

__all__ = [
    "UIActionTracker",
    "UIActionFailureTracker",
    "UICommander",
    "LiveComponent",
    "MixedStateComponent",
]
