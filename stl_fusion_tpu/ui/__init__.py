"""UI layer (SURVEY.md §2.7): live components + action tracking + browser push."""
from .action_tracker import UIActionFailureTracker, UIActionTracker, UICommander
from .auth_state import AuthState, AuthStateProvider
from .live_component import LiveComponent, MixedStateComponent
from .web import HtmlComponent, LiveViewServer

__all__ = [
    "UIActionTracker",
    "UIActionFailureTracker",
    "UICommander",
    "AuthState",
    "AuthStateProvider",
    "LiveComponent",
    "MixedStateComponent",
    "HtmlComponent",
    "LiveViewServer",
]
