"""UI layer (SURVEY.md §2.7): live components + action tracking."""
from .action_tracker import UIActionTracker, UICommander
from .live_component import LiveComponent, MixedStateComponent

__all__ = ["UIActionTracker", "UICommander", "LiveComponent", "MixedStateComponent"]
