"""UIActionTracker + UICommander — instant-update windows after user actions.

Re-expression of src/Stl.Fusion/UI/ — UIActionTracker.cs:3-60 and
UICommander.cs: when the user triggers a command, states watching through an
UpdateDelayer skip their debounce (the "instant updates right after my own
action" UX rule). The tracker counts running actions and exposes awaitable
action/result events.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Optional

from ..utils.async_utils import AsyncEvent

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["UIActionTracker", "UICommander", "UIActionFailureTracker"]


class UIActionTracker:
    def __init__(self, instant_update_period: float = 0.3):
        self.instant_update_period = instant_update_period
        self.running_action_count = 0
        self._action_event: AsyncEvent = AsyncEvent(None)
        self._result_event: AsyncEvent = AsyncEvent(None)
        self._last_action_at: float = 0.0
        #: sync listeners ``(command, error) -> None`` fired on completion
        self.on_completed: list = []

    @property
    def are_instant_updates_enabled(self) -> bool:
        if self.running_action_count > 0:
            return True
        return (time.monotonic() - self._last_action_at) < self.instant_update_period

    def action_started(self, command: Any) -> None:
        self.running_action_count += 1
        self._last_action_at = time.monotonic()
        self._action_event = self._action_event.latest().create_next(command)

    def action_completed(self, command: Any, error: Optional[BaseException]) -> None:
        self.running_action_count = max(0, self.running_action_count - 1)
        self._last_action_at = time.monotonic()
        self._result_event = self._result_event.latest().create_next((command, error))
        for listener in list(self.on_completed):
            # a raising listener must not mask the command's real outcome
            # (action_completed runs in UICommander.call's finally) or
            # starve the remaining listeners
            try:
                listener(command, error)
            except Exception:
                log.exception("on_completed listener failed")

    async def when_action(self) -> Any:
        return (await self._action_event.latest().when_next()).value

    async def when_result(self) -> Any:
        return (await self._result_event.latest().when_next()).value


class UICommander:
    """Commander facade that reports into the action tracker."""

    def __init__(self, commander, tracker: Optional[UIActionTracker] = None):
        self.commander = commander
        self.tracker = tracker or UIActionTracker()

    async def call(self, command: Any) -> Any:
        self.tracker.action_started(command)
        error: Optional[BaseException] = None
        try:
            return await self.commander.call(command)
        except BaseException as e:
            error = e
            raise
        finally:
            self.tracker.action_completed(command, error)


class UIActionFailureTracker:
    """Bounded list of recent failed UI actions (≈ UI/UIActionFailureTracker
    in the reference): UIs bind it to render error toasts/banners; entries
    clear individually (user dismissed) or wholesale (navigation)."""

    def __init__(self, tracker: UIActionTracker, max_failures: int = 16):
        self.tracker = tracker
        self.max_failures = max_failures
        self.failures: list = []  # (command, error) newest-last
        self._listeners: list = []
        tracker.on_completed.append(self._on_completed)

    def _on_completed(self, command, error) -> None:
        if error is None:
            return
        self.failures.append((command, error))
        del self.failures[: max(0, len(self.failures) - self.max_failures)]
        for listener in list(self._listeners):
            try:
                listener(command, error)
            except Exception:
                log.exception("on_failure listener failed")

    def on_failure(self, listener) -> None:
        self._listeners.append(listener)

    def dismiss(self, index: int) -> None:
        if 0 <= index < len(self.failures):
            del self.failures[index]

    def clear(self) -> None:
        self.failures.clear()

    def __len__(self) -> int:
        return len(self.failures)
