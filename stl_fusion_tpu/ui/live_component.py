"""LiveComponent — the ComputedStateComponent analogue for Python UIs.

Re-expression of src/Stl.Fusion.Blazor/Components/ —
StatefulComponentBase / ComputedStateComponent.cs:27-132 /
MixedStateComponent.cs, re-targeted from Blazor render trees to any Python
UI surface (server-rendered HTML over the RPC push channel, a TUI, a
websocket frontend): a component owns a ComputedState whose recomputations
drive ``render()``; parameter changes recompute only when the parameters
actually differ (the ParameterComparer rule).
"""
from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from ..core.hub import FusionHub
from ..core.options import ComputedOptions
from ..state.computed_state import ComputedState
from ..state.delayer import FixedDelayer, UpdateDelayer
from ..state.mutable import MutableState

T = TypeVar("T")
log = logging.getLogger("stl_fusion_tpu")

__all__ = ["LiveComponent", "MixedStateComponent"]


class LiveComponent(Generic[T]):
    """Owns a ComputedState; re-renders on every consistent update.

    Subclasses implement ``compute_state()`` (the reactive read) and
    ``render(value)`` (the output side-effect: send HTML patch, redraw,
    notify websocket...).
    """

    def __init__(
        self,
        hub: Optional[FusionHub] = None,
        update_delayer: Optional[UpdateDelayer] = None,
        options: Optional[ComputedOptions] = None,
        name: Optional[str] = None,
    ):
        self._hub = hub
        self._delayer = update_delayer or FixedDelayer.ZERO_UNSAFE
        self._options = options
        self._name = name or type(self).__name__
        self.state: Optional[ComputedState] = None
        self.render_count = 0
        self.parameters: Dict[str, Any] = {}
        self._render_task: Optional[asyncio.Task] = None

    # -- lifecycle ---------------------------------------------------------
    def mount(self) -> "LiveComponent":
        self.state = ComputedState(
            self.compute_state,
            self._hub,
            self._options,
            self._delayer,
            name=f"component:{self._name}",
        )
        self.state.updated_handlers.append(self._on_updated)
        self.state.start()
        return self

    async def unmount(self) -> None:
        if self.state is not None:
            await self.state.dispose()
            self.state = None

    # -- parameters (ParameterComparer semantics) -------------------------
    async def set_parameters(self, **params: Any) -> None:
        """Recompute ONLY if a parameter actually changed
        (≈ ComponentInfo.ShouldSetParameters)."""
        changed = any(self.parameters.get(k) != v for k, v in params.items())
        self.parameters.update(params)
        if changed and self.state is not None:
            await self.state.recompute()

    # -- reactive read + render -------------------------------------------
    async def compute_state(self) -> T:
        raise NotImplementedError

    def render(self, value: T) -> None:
        raise NotImplementedError

    def render_error(self, error: BaseException) -> None:
        log.debug("%s render error: %s", self._name, error)

    def _on_updated(self, state) -> None:
        self.render_count += 1
        out = state.snapshot.computed._output
        if out is None:
            return
        try:
            if out.has_error:
                self.render_error(out.error)
            else:
                self.render(out.value)
        except Exception:  # noqa: BLE001
            log.exception("%s render failed", self._name)

    async def when_rendered(self, min_count: int = 1, timeout: float = 5.0) -> None:
        async def wait():
            while self.render_count < min_count:
                await asyncio.sleep(0.005)

        await asyncio.wait_for(wait(), timeout)


class MixedStateComponent(LiveComponent[T]):
    """LiveComponent + a MutableState input (≈ MixedStateComponent.cs):
    local user input that recomputes the view state when set."""

    def __init__(self, initial_input: Any = None, **kwargs):
        super().__init__(**kwargs)
        self.mutable_state: MutableState = MutableState(initial_input, kwargs.get("hub"))

    def set_input(self, value: Any) -> None:
        self.mutable_state.set(value)
