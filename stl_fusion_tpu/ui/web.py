"""LiveViewServer — LiveComponent renders pushed to real browsers.

The browser-facing end of the UI layer (VERDICT r1 missing #4): where the
reference mounts ComputedStateComponent in a Blazor circuit and lets
SignalR ship render-tree patches (samples/TodoApp/UI,
src/Stl.Fusion.Blazor/Components/ComputedStateComponent.cs:27-132), here a
plain browser opens a websocket and receives each component render as a
JSON payload ``{"html": ...}`` (or whatever the component's ``render``
pushes). The reactive machinery is identical — a ComputedState recomputes
on invalidation and drives ``render()`` — only the transport differs:
JSON-over-websocket instead of a Blazor circuit, because there is no .NET
runtime in the browser to host one.

One component instance exists PER CONNECTION (the Blazor circuit scoping
rule): the factory receives a ``push(payload)`` callable bound to that
socket and returns an UNMOUNTED LiveComponent; the server mounts it on
connect and unmounts it on disconnect, so a closed tab stops consuming
invalidations.
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Callable, Optional

from .live_component import LiveComponent

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["LiveViewServer", "HtmlComponent"]


class HtmlComponent(LiveComponent):
    """LiveComponent whose renders push ``{"html": ...}`` to one browser
    socket. Subclasses implement ``compute_state()`` (the reactive read)
    and ``to_html(value)``."""

    def __init__(self, push: Callable[[Any], None], **kwargs):
        super().__init__(**kwargs)
        self.push = push

    def to_html(self, value: Any) -> str:
        raise NotImplementedError

    def render(self, value: Any) -> None:
        self.push({"html": self.to_html(value)})

    def render_error(self, error: BaseException) -> None:
        self.push({"error": f"{type(error).__name__}: {error}"})


class LiveViewServer:
    """Hosts per-connection LiveComponents over plain-JSON websockets."""

    def __init__(
        self,
        component_factory: Callable[[Callable[[Any], None]], LiveComponent],
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.component_factory = component_factory
        self.host = host
        self.port = port
        self.connections = 0
        self._server = None

    async def start(self) -> "LiveViewServer":
        from websockets.asyncio.server import serve

        self._server = await serve(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}/live"

    async def _handle(self, ws) -> None:
        # renders may fire from any task; a queue decouples them from the
        # socket writer so a slow browser never blocks the compute loop
        queue: "asyncio.Queue[Any]" = asyncio.Queue()
        component = self.component_factory(queue.put_nowait)
        component.mount()
        self.connections += 1

        async def pump() -> None:
            while True:
                payload = await queue.get()
                await ws.send(json.dumps(payload))

        pump_task = asyncio.ensure_future(pump())
        try:
            # hold until the browser goes away; inbound messages reach the
            # component's optional on_message (local-input hook, ≈ the
            # MixedStateComponent input path)
            async for raw in ws:
                handler = getattr(component, "on_message", None)
                if handler is not None:
                    await handler(raw)
        except Exception:  # noqa: BLE001 — a dying socket is a normal exit
            pass
        finally:
            self.connections -= 1
            pump_task.cancel()
            await component.unmount()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
