"""LiveViewServer — LiveComponent renders pushed to real browsers.

The browser-facing end of the UI layer (VERDICT r1 missing #4): where the
reference mounts ComputedStateComponent in a Blazor circuit and lets
SignalR ship render-tree patches (samples/TodoApp/UI,
src/Stl.Fusion.Blazor/Components/ComputedStateComponent.cs:27-132), here a
plain browser opens a websocket and receives each component render as a
JSON payload ``{"html": ...}`` (or whatever the component's ``render``
pushes). The reactive machinery is identical — a ComputedState recomputes
on invalidation and drives ``render()`` — only the transport differs:
JSON-over-websocket instead of a Blazor circuit, because there is no .NET
runtime in the browser to host one.

One component instance exists PER CONNECTION (the Blazor circuit scoping
rule): the factory receives a ``push(payload)`` callable bound to that
socket and returns an UNMOUNTED LiveComponent; the server mounts it on
connect and unmounts it on disconnect, so a closed tab stops consuming
invalidations.
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Callable, Optional

from ..edge.session import LatestWinsMailbox, pump_payloads
from ..utils.async_utils import TaskSet
from .live_component import LiveComponent

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["LiveViewServer", "HtmlComponent"]


class HtmlComponent(LiveComponent):
    """LiveComponent whose renders push ``{"html": ...}`` to one browser
    socket. Subclasses implement ``compute_state()`` (the reactive read)
    and ``to_html(value)``."""

    def __init__(self, push: Callable[[Any], None], **kwargs):
        super().__init__(**kwargs)
        self.push = push

    def to_html(self, value: Any) -> str:
        raise NotImplementedError

    def render(self, value: Any) -> None:
        self.push({"html": self.to_html(value)})

    def render_error(self, error: BaseException) -> None:
        self.push({"error": f"{type(error).__name__}: {error}"})


#: the per-connection latest-wins mailbox now lives in the shared edge
#: session core (ISSUE 8 satellite: the UI layer rides the same bounded-
#: outbox machinery as the edge gateway's SSE/WebSocket sessions); the
#: historic name stays importable — behavior is byte-identical
_RenderSlot = LatestWinsMailbox


class LiveViewServer:
    """Hosts per-connection LiveComponents over plain-JSON websockets.

    Delivery rides the shared edge session core (edge/session.py):
    latest-wins per connection (see :class:`LatestWinsMailbox`);
    ``min_send_interval`` optionally rate-limits pushes (the newest payload
    at the end of the interval is what ships); ``heartbeat_interval``
    keeps idle connections alive with ``{"ping": t}`` frames (off by
    default — historic wire behavior); and a send that can't make
    progress for ``send_timeout`` seconds — a browser that stopped reading
    while the transport buffer is full — EVICTS the connection, unmounting
    its component so it stops consuming invalidations."""

    def __init__(
        self,
        component_factory: Callable[[Callable[[Any], None]], LiveComponent],
        host: str = "127.0.0.1",
        port: int = 0,
        min_send_interval: float = 0.0,
        send_timeout: Optional[float] = 30.0,
        heartbeat_interval: Optional[float] = None,
    ):
        self.component_factory = component_factory
        self.host = host
        self.port = port
        self.min_send_interval = min_send_interval
        self.send_timeout = send_timeout
        self.heartbeat_interval = heartbeat_interval
        self.connections = 0
        self.evictions = 0  # observability: slow clients closed mid-send
        self._server = None
        #: eviction-close side tasks, owned so stop() cancels a close still
        #: in flight instead of leaking it (fusionlint FL003)
        self._side_tasks = TaskSet(name="live-view-side")

    async def start(self) -> "LiveViewServer":
        from websockets.asyncio.server import serve

        self._server = await serve(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}/live"

    async def _handle(self, ws) -> None:
        # renders may fire from any task; the slot decouples them from the
        # socket writer so a slow browser never blocks the compute loop —
        # and, latest-wins, never accumulates stale intermediate renders
        slot = _RenderSlot()
        component = self.component_factory(slot.push)
        component.mount()
        self.connections += 1
        loop = asyncio.get_running_loop()

        async def send(payload) -> None:
            await ws.send(json.dumps(payload))

        async def heartbeat() -> None:
            await ws.send(json.dumps({"ping": loop.time()}))

        def on_evict() -> None:
            # the browser stopped draining: evict it rather than letting a
            # dead tab pin the component forever. Abort — a graceful close
            # would wait close_timeout for a close handshake the dead peer
            # will never answer, through the very buffer that is already
            # full
            self.evictions += 1
            transport = getattr(ws, "transport", None)
            if transport is not None:
                transport.abort()
            else:
                try:
                    self._side_tasks.spawn(ws.close())
                except RuntimeError:  # server stopped: socket dies with it
                    pass

        pump_task = asyncio.ensure_future(
            pump_payloads(
                slot,
                send,
                min_send_interval=self.min_send_interval,
                send_timeout=self.send_timeout,
                heartbeat_interval=self.heartbeat_interval,
                heartbeat=heartbeat,
                on_evict=on_evict,
            )
        )
        try:
            # hold until the browser goes away; inbound messages reach the
            # component's optional on_message (local-input hook, ≈ the
            # MixedStateComponent input path)
            async for raw in ws:
                handler = getattr(component, "on_message", None)
                if handler is not None:
                    await handler(raw)
        except Exception:  # noqa: BLE001 — a dying socket is a normal exit
            pass
        finally:
            self.connections -= 1
            pump_task.cancel()
            await component.unmount()

    async def stop(self) -> None:
        await self._side_tasks.aclose()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
