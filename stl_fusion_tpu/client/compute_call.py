"""Compute-call RPC type: calls that carry invalidation subscriptions.

Re-expression of src/Stl.Fusion/Client/Internal/ — RpcOutboundComputeCall
(:11-109), RpcInboundComputeCall (:20-106), RpcComputeSystemCalls (:11-27):

- the server runs the target under dependency capture, attaches the
  computed's version as the ``@version`` header, sends the result, then
  **keeps the call registered and awaits the computed's invalidation**;
  when it fires, it pushes a ``$sys-c.invalidate`` (fire-and-forget) tagged
  with the call id and only then completes;
- the client resolves the pushed invalidation to the outbound call, which
  invalidates its bound ClientComputed — re-entering the local cascade.

This is THE mechanism that makes a remote cache coherent: every remote read
is implicitly a subscription.
"""
from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Any, Optional

from ..core.context import try_capture
from ..utils.ltag import LTag
from ..utils.serialization import dumps, loads
from ..rpc.calls import RpcInboundCall, RpcOutboundCall
from ..rpc.message import (
    CALL_TYPE_COMPUTE,
    COMPUTE_SYSTEM_SERVICE,
    VERSION_HEADER,
    RpcMessage,
)

if TYPE_CHECKING:
    from ..rpc.hub import RpcHub
    from ..rpc.peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "ResultMissedError",
    "RpcOutboundComputeCall",
    "RpcInboundComputeCall",
    "install_compute_call_type",
]


class ResultMissedError(Exception):
    """An invalidation arrived while the call's result was still pending —
    no result is coming (e.g. the server answered a re-sent call with
    invalidate-only). Retriable: the client just re-issues the call."""


class RpcOutboundComputeCall(RpcOutboundCall):
    call_type_id = CALL_TYPE_COMPUTE

    def __init__(self, peer, service, method, args, no_wait=False):
        super().__init__(peer, service, method, args, no_wait)
        self.result_version: Optional[LTag] = None
        self.when_invalidated: asyncio.Future = asyncio.get_event_loop().create_future()

    def set_result(self, value: Any, message: RpcMessage) -> None:
        v = message.header(VERSION_HEADER)
        version = LTag.parse(v) if v else None
        if self.future is not None and self.future.done():
            # a REDELIVERED result (reconnect re-send): the original answer
            # was already consumed. A version that moved on means the server
            # recomputed while the link was down — and the invalidation for
            # OUR version died with the old link (sent into a buffer the
            # link took down with it). Without this check the bound computed
            # stays consistent-but-stale FOREVER (≈ the reference's
            # version-mismatch handling, RpcOutboundComputeCall.cs:71-109).
            if (
                version is not None
                and self.result_version is not None
                and version != self.result_version
            ):
                self.set_invalidated()
            return
        self.result_version = version
        # compute calls STAY registered — the invalidation push arrives later
        if self.future is not None:
            self.future.set_result(value)

    def set_error(self, error: BaseException) -> None:
        super().set_error(error)
        self.set_invalidated()  # an errored call can't deliver invalidations

    def set_invalidated(self) -> None:
        """Single-connection delivery is ordered (result, then invalidate —
        the reference leans on that, RpcOutboundComputeCall.cs:71-83), but
        two of our paths deliver an invalidate while the result future is
        still pending: the reconnect-riding invalidation sender racing a
        re-sent result, and the server's restart() answering a re-sent call
        with invalidate-ONLY when its computed is already stale. No result
        can be counted on after that, so a pending future fails with the
        retriable ``ResultMissedError`` (the client's already-invalidated
        retry loop handles it) instead of parking the caller forever."""
        if self.future is not None and not self.future.done():
            self.future.set_exception(
                ResultMissedError(f"invalidation overtook the result of call {self.call_id}")
            )
        if not self.when_invalidated.done():
            self.when_invalidated.set_result(None)
        self.peer.outbound_calls.pop(self.call_id, None)

    def unregister(self) -> None:
        self.peer.outbound_calls.pop(self.call_id, None)


class RpcInboundComputeCall(RpcInboundCall):
    def __init__(self, peer, message):
        super().__init__(peer, message)
        self.computed = None

    async def _run(self) -> None:
        try:
            computed = await self._capture_target()
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        except Exception as e:  # noqa: BLE001 — capture failed outright
            await self.send_error(e)
            self.peer.inbound_calls.pop(self.call_id, None)
            return
        self.computed = computed
        headers = ((VERSION_HEADER, computed.version.format()),)
        out = computed._output
        if out is not None and out.has_error:
            await self.send_error(out.error)  # errors carry no subscription
            self.peer.inbound_calls.pop(self.call_id, None)
            return
        try:
            # send_ok's delivery swallows TRANSPORT failures itself
            # (restart() re-sends); what reaches here is a serialization
            # or middleware failure — the client must error, not hang
            await self.send_ok(out.value if out is not None else None, headers=headers)
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        except Exception as e:  # noqa: BLE001
            try:
                await self.send_error(e)
            except Exception:  # noqa: BLE001
                pass
            self.peer.inbound_calls.pop(self.call_id, None)
            return
        # stay registered; push $sys-c.invalidate when the computed dies
        asyncio.get_event_loop().create_task(self._watch_invalidation(computed))

    def restart(self) -> None:
        """Re-delivery after reconnect: if our computed already died, the
        result is stale — push the invalidation instead (≈ version-mismatch
        handling, RpcInboundCall.Restart + RpcOutboundComputeCall version
        checks)."""
        if self.computed is not None and self.computed.is_invalidated:
            asyncio.get_event_loop().create_task(self._send_invalidation())
        else:
            super().restart()

    async def _capture_target(self):
        from ..core.context import suspend_dependency_capture

        args = loads(self.message.argument_data)
        service_def = self.peer.hub.service_registry.require(self.message.service)
        method = service_def.method(self.message.method)
        with suspend_dependency_capture():  # RPC boundary: no cross-wire edges
            computed = await try_capture(lambda: method.fn(*args))
        if computed is None:
            raise RuntimeError(
                f"{self.message.service}.{self.message.method} is not a compute method "
                f"(nothing was captured)"
            )
        return computed

    async def _watch_invalidation(self, computed) -> None:
        try:
            await computed.when_invalidated()
            await self._send_invalidation()
        except asyncio.CancelledError:
            pass
        finally:
            self.peer.inbound_calls.pop(self.call_id, None)

    async def _send_invalidation(self, max_attempts: int = 100) -> None:
        """Deliver $sys-c.invalidate, riding out reconnects: the subscription
        must not be lost just because the link was down when it fired."""
        message = RpcMessage(
            call_type_id=CALL_TYPE_COMPUTE,
            call_id=self.call_id,
            service=COMPUTE_SYSTEM_SERVICE,
            method="invalidate",
            argument_data=dumps([self.call_id]),
        )
        for _ in range(max_attempts):
            try:
                await self.peer.send(message)
                return
            except Exception:  # noqa: BLE001 — wait for the link to return
                ev = self.peer.connection_state.latest()
                if ev.value.is_connected:
                    await asyncio.sleep(0.05)
                else:
                    try:
                        await asyncio.wait_for(ev.when(lambda s: s.is_connected), 30.0)
                    except asyncio.TimeoutError:
                        return  # client is gone; it will resubscribe on return

    def on_completed(self) -> None:
        pass  # compute calls manage their own registration lifetime


def install_compute_call_type(rpc_hub: "RpcHub") -> None:
    """Register call type 1 + the $sys-c dispatcher on an RPC hub
    (≈ RpcComputeCallType.cs registration)."""
    rpc_hub.call_types.register(CALL_TYPE_COMPUTE, RpcOutboundComputeCall, RpcInboundComputeCall)

    def handle_compute_system(peer: "RpcPeer", message: RpcMessage) -> None:
        if message.method == "invalidate":
            (call_id,) = loads(message.argument_data)
            call = peer.outbound_calls.get(call_id)
            if isinstance(call, RpcOutboundComputeCall):
                call.set_invalidated()

    rpc_hub.compute_system_handler = handle_compute_system
