"""Compute-call RPC type: calls that carry invalidation subscriptions.

Re-expression of src/Stl.Fusion/Client/Internal/ — RpcOutboundComputeCall
(:11-109), RpcInboundComputeCall (:20-106), RpcComputeSystemCalls (:11-27):

- the server runs the target under dependency capture, attaches the
  computed's version as the ``@version`` header, sends the result, then
  **keeps the call registered and awaits the computed's invalidation**;
  when it fires, it pushes a ``$sys-c.invalidate`` (fire-and-forget) tagged
  with the call id and only then completes;
- the client resolves the pushed invalidation to the outbound call, which
  invalidates its bound ClientComputed — re-entering the local cascade.

This is THE mechanism that makes a remote cache coherent: every remote read
is implicitly a subscription.

ISSUE 11 adds the BATCHED flavor of the same contract (the upstream value
plane's level 1): ``$sys-c.recompute_batch`` carries a whole fence-burst's
worth of per-key compute calls in ONE frame — each entry is a real
client-allocated outbound call (so reconnect re-send, redelivery dedup and
the invalidation subscription machinery are IDENTICAL to the per-key
path), but the RPC/codec/loop-hop envelope is paid once per burst instead
of once per key. The server answers every successfully-captured entry in
ONE ``recompute_batch_r`` frame; per-entry failures are answered through
the ordinary per-call ``$sys.error`` wire shape so the client's routing /
retry semantics (ShardMovedError, ResultMissedError) stay byte-identical
with the per-key path. Entries may additionally request PUBLISH mode
(level 2): the serving member then keeps a standing registration
(rpc/fanout.py ``WaveValuePublisher``) and answers later wave fences with
pushed ``value_block`` frames instead of plain invalidations.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Any, Optional

from ..core.context import try_capture
from ..diagnostics.flight_recorder import RECORDER, call_key
from ..diagnostics.metrics import global_metrics
from ..utils.errors import ExceptionInfo
from ..utils.ltag import LTag
from ..utils.serialization import dumps, loads
from ..rpc.calls import RpcInboundCall, RpcOutboundCall
from ..rpc.message import (
    CALL_TYPE_COMPUTE,
    COMPUTE_SYSTEM_SERVICE,
    SYSTEM_SERVICE,
    VERSION_HEADER,
    RpcMessage,
)

if TYPE_CHECKING:
    from ..rpc.hub import RpcHub
    from ..rpc.peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "ResultMissedError",
    "RpcOutboundComputeCall",
    "RpcInboundComputeCall",
    "install_compute_call_type",
]


class ResultMissedError(Exception):
    """An invalidation arrived while the call's result was still pending —
    no result is coming (e.g. the server answered a re-sent call with
    invalidate-only). Retriable: the client just re-issues the call."""


#: cached delivery histogram: set_invalidated runs once per applied key in
#: a fan-out burst, and a registry get-or-create there (name sanitize +
#: lock) would tax the exact path PR 2 optimized. Cached once; a test that
#: clears the global registry mid-run keeps recording into the detached
#: instance (nothing in-repo does that).
_delivery_hist = None


def _record_delivery(delta_ms: float, cause: Optional[str] = None) -> None:
    global _delivery_hist
    h = _delivery_hist
    if h is None:
        h = _delivery_hist = global_metrics().histogram(
            "fusion_e2e_delivery_ms",
            help="server wave apply -> client invalidation apply",
        )
    # cause rides into the histogram's exemplar ring (ISSUE 19): a tail
    # delivery sample keeps the wave id that produced it, so an alert on
    # this histogram links to GET /trace?cause= in one hop
    h.record(delta_ms, cause=cause)


class RpcOutboundComputeCall(RpcOutboundCall):
    call_type_id = CALL_TYPE_COMPUTE

    def __init__(self, peer, service, method, args, no_wait=False, headers=()):
        super().__init__(peer, service, method, args, no_wait, headers)
        self.result_version: Optional[LTag] = None
        #: cause id of the server-side wave/span whose invalidation fenced
        #: this call (ISSUE 3 trace propagation); None until invalidated or
        #: when the server predates cause stamping
        self.invalidation_cause: Optional[str] = None
        #: server-side wave-apply timestamp the fence carried (perf_counter
        #: epoch — trustworthy same-host only, like the delivery histogram).
        #: Kept so a DOWNSTREAM tier (the edge gateway, ISSUE 8) can extend
        #: the delivery measurement one more hop: fence → edge → session.
        self.invalidation_origin_ts: Optional[float] = None
        self.when_invalidated: asyncio.Future = asyncio.get_event_loop().create_future()
        #: True when this call rode a ``recompute_batch`` entry that asked
        #: for publish mode AND the server armed a standing registration —
        #: later fences for this key arrive as ``value_block`` pushes, not
        #: plain invalidations (the edge's zero-RPC path, ISSUE 11)
        self.publish_armed = False
        #: sync callbacks run INSIDE set_invalidated — the bound
        #: ClientComputed invalidates in the same dispatch that applied the
        #: frame instead of one call_soon hop later; at fan-out scale those
        #: hops were a measurable share of the staleness window
        self.invalidated_callbacks: list = []

    def set_result(self, value: Any, message: RpcMessage) -> None:
        v = message.header(VERSION_HEADER)
        version = LTag.parse(v) if v else None
        if self.future is not None and self.future.done():
            # a REDELIVERED result (reconnect re-send): the original answer
            # was already consumed. A version that moved on means the server
            # recomputed while the link was down — and the invalidation for
            # OUR version died with the old link (sent into a buffer the
            # link took down with it). Without this check the bound computed
            # stays consistent-but-stale FOREVER (≈ the reference's
            # version-mismatch handling, RpcOutboundComputeCall.cs:71-109).
            if (
                version is not None
                and self.result_version is not None
                and version != self.result_version
            ):
                self.set_invalidated()
            return
        self.result_version = version
        # compute calls STAY registered — the invalidation push arrives later
        if self.future is not None:
            self.future.set_result(value)

    def set_error(self, error: BaseException) -> None:
        super().set_error(error)
        self.set_invalidated()  # an errored call can't deliver invalidations

    def set_invalidated(self, cause: Optional[str] = None, origin_ts: Optional[float] = None) -> None:
        """Single-connection delivery is ordered (result, then invalidate —
        the reference leans on that, RpcOutboundComputeCall.cs:71-83), but
        two of our paths deliver an invalidate while the result future is
        still pending: the reconnect-riding invalidation sender racing a
        re-sent result, and the server's restart() answering a re-sent call
        with invalidate-ONLY when its computed is already stale. No result
        can be counted on after that, so a pending future fails with the
        retriable ``ResultMissedError`` (the client's already-invalidated
        retry loop handles it) instead of parking the caller forever.

        ``cause``/``origin_ts`` arrive from the ``$sys-c`` frame: the cause
        links this fence to its originating server wave; the origin
        timestamp yields the end-to-end delivery sample recorded into the
        process histogram (``fusion_e2e_delivery_ms``). The timestamp is
        the sender's ``perf_counter`` value; since ISSUE 9 it is mapped
        onto the local timeline through the peer's probed clock offset
        (diagnostics/clocksync.py — one NTP-style probe per connect, so
        cross-host samples are accurate to ~RTT/2 instead of meaningless).
        Never-probed peers keep the identity mapping, which is exact for
        the in-process / same-host stacks. The range guard below remains
        the belt for unprobed cross-host epochs."""
        if cause is not None:
            self.invalidation_cause = cause
        if RECORDER.enabled:
            # the client end of the causal chain: explain() on this process
            # reads these to say WHO fenced the key (and the cause joins
            # back to the server's wave/span over the $sys-d hop)
            RECORDER.note(
                "fenced",
                key=call_key(self.service, self.method, self.args),
                cause=cause,
                detail=f"call#{self.call_id} peer={getattr(self.peer, 'ref', '?')}",
            )
        if origin_ts is not None:
            # map the sender's perf_counter stamp onto the LOCAL timeline
            # through the peer's probed clock offset (ISSUE 9: cross-host
            # clock-safe delivery timestamps — identity for never-probed
            # same-clock stacks, so in-process transports keep the exact
            # old behavior). The corrected value is what we STORE, so the
            # edge tier's delivery hop inherits the correction for free.
            from ..diagnostics.clocksync import global_clock_sync

            origin_ts = global_clock_sync().to_local(
                getattr(self.peer, "ref", None), origin_ts
            )
            self.invalidation_origin_ts = origin_ts
            delta_ms = (time.perf_counter() - origin_ts) * 1e3
            if 0.0 <= delta_ms < 3.6e6:  # range guard, NOT skew detection
                _record_delivery(delta_ms, cause=cause)
        if self.future is not None and not self.future.done():
            self.future.set_exception(
                ResultMissedError(f"invalidation overtook the result of call {self.call_id}")
            )
        if not self.when_invalidated.done():
            self.when_invalidated.set_result(None)
            callbacks, self.invalidated_callbacks = self.invalidated_callbacks, []
            for cb in callbacks:
                cb()
        self.peer.outbound_calls.pop(self.call_id, None)

    def set_batch_result(self, version: Optional[str], value: Any, publish_armed: bool = False) -> None:
        """Result delivery through a ``recompute_batch_r`` frame entry —
        the batched twin of :meth:`set_result` (version rides inline in
        the entry instead of as a ``@version`` header). The redelivered-
        result version-mismatch rule applies unchanged: a done future with
        a moved-on version means the invalidation for OUR version died
        with an old link."""
        v = LTag.parse(version) if version else None
        if self.future is not None and self.future.done():
            if (
                v is not None
                and self.result_version is not None
                and v != self.result_version
            ):
                self.set_invalidated()
            return
        self.publish_armed = bool(publish_armed)
        self.result_version = v
        if self.future is not None:
            self.future.set_result(value)

    def unregister(self) -> None:
        self.peer.outbound_calls.pop(self.call_id, None)


class RpcInboundComputeCall(RpcInboundCall):
    def __init__(self, peer, message):
        super().__init__(peer, message)
        self.computed = None
        self._fanout_nid = None  # registered in the hub's ComputeFanoutIndex
        #: set by the fanout index when a wave drain already shipped this
        #: subscription's invalidation — the watch task must not re-send
        self._invalidation_pushed = False

    async def _run(self) -> None:
        try:
            computed = await self._capture_target()
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        except Exception as e:  # noqa: BLE001 — capture failed outright
            await self.send_error(e)
            self.peer.inbound_calls.pop(self.call_id, None)
            return
        self.computed = computed
        headers = ((VERSION_HEADER, computed.version.format()),)
        out = computed._output
        if out is not None and out.has_error:
            await self.send_error(out.error)  # errors carry no subscription
            self.peer.inbound_calls.pop(self.call_id, None)
            return
        try:
            # send_ok's delivery swallows TRANSPORT failures itself
            # (restart() re-sends); what reaches here is a serialization
            # or middleware failure — the client must error, not hang
            await self.send_ok(out.value if out is not None else None, headers=headers)
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        except Exception as e:  # noqa: BLE001
            try:
                await self.send_error(e)
            except Exception:  # noqa: BLE001
                pass
            self.peer.inbound_calls.pop(self.call_id, None)
            return
        self._arm_subscription(computed)

    def _arm_subscription(self, computed) -> None:
        """Stay registered; push $sys-c when the computed dies. The push is
        armed as a SYNC on_invalidated handler, not a parked watch task:
        under coalescing the push is a dict insert into the peer outbox
        (flushed as one $sys-c.invalidate_batch per tick), so a burst
        fencing 10k subscriptions costs 10k inserts + N frames — not 10k
        task wakeups + 10k awaited sends. Graph-resident computeds ALSO
        index into the hub's fanout index (rpc/fanout.py) so a device
        burst's newly-mask drains them during wave application; the
        handler then just cleans up (``_invalidation_pushed``).
        (index registration honors the wire-compat flag: a hub serving
        per-key frames must not let the mask drain ship batch frames)"""
        fanout = getattr(self.peer.hub, "compute_fanout", None)
        nid = getattr(computed, "_backend_nid", None)
        if (
            fanout is not None
            and nid is not None
            and getattr(self.peer.hub, "coalesce_invalidations", True)
        ):
            self._fanout_nid = nid
            fanout.register(
                nid, self.peer, self.call_id, computed.version.format(), call=self
            )
        computed.on_invalidated(self._on_computed_invalidated)

    async def serve_inline(self, publish: bool = False):
        """Batch-entry flavor of :meth:`_run` (``recompute_batch``, ISSUE
        11): capture + arm the subscription exactly like a per-key call,
        but RETURN the response entry ``[call_id, version, value,
        publish_armed]`` for the caller to fold into ONE
        ``recompute_batch_r`` frame instead of sending a per-call reply.
        Failures (capture errors AND memoized compute errors) are answered
        through the ordinary per-call ``$sys.error`` wire shape — the
        client's per-key fallback ladder owns them — and return None.

        With ``publish`` (and a :class:`~..rpc.fanout.WaveValuePublisher`
        installed on the hub) the captured computed additionally registers
        a STANDING publish subscription: later wave fences ship a pushed
        ``value_block`` entry instead of a plain invalidation."""
        self.peer.inbound_calls[self.call_id] = self
        try:
            computed = await self._capture_target()
        except asyncio.CancelledError:
            self.peer.inbound_calls.pop(self.call_id, None)
            raise
        except Exception as e:  # noqa: BLE001 — capture failed outright
            self.peer.inbound_calls.pop(self.call_id, None)
            await self._send_entry_error(e)
            return None
        self.computed = computed
        out = computed._output
        if out is not None and out.has_error:
            self.peer.inbound_calls.pop(self.call_id, None)
            await self._send_entry_error(out.error)
            return None
        armed = False
        if publish:
            publisher = getattr(self.peer.hub, "value_publisher", None)
            if publisher is not None:
                armed = publisher.register_standing(
                    self.peer,
                    self.call_id,
                    self.message.service,
                    self.message.method,
                    loads(self.message.argument_data),
                    computed,
                )
        self._arm_subscription(computed)
        return [
            self.call_id,
            computed.version.format(),
            out.value if out is not None else None,
            armed,
        ]

    async def _send_entry_error(self, error: BaseException) -> None:
        """Per-entry error reply for the batch path — the per-key wire
        shape ($sys.error with this entry's call id), so the client's
        existing completion/ShardMoved handling applies untouched. A
        transport death is swallowed: the client's reconnect re-send
        replays the entry as an ordinary per-key call."""
        try:
            await self.peer.send(self._error_message(error))
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — link died; reconnect re-serves
            pass

    def restart(self) -> None:
        """Re-delivery after reconnect: if our computed already died, the
        result is stale — push the invalidation instead (≈ version-mismatch
        handling, RpcInboundCall.Restart + RpcOutboundComputeCall version
        checks). A batch-served call (``serve_inline``) stored no
        result_message — rebuild the per-key OK reply from the live
        computed so the client's re-sent call never hangs."""
        if self.computed is not None and self.computed.is_invalidated:
            self.peer.track_side_task(
                asyncio.get_event_loop().create_task(self._send_invalidation())
            )
        elif self.result_message is None and self.computed is not None:
            out = self.computed._output
            headers = ((VERSION_HEADER, self.computed.version.format()),)
            try:
                if out is not None and out.has_error:
                    self._build_error(out.error)
                else:
                    self._build_ok(
                        out.value if out is not None else None, headers=headers
                    )
            except Exception:  # noqa: BLE001 — unserializable: invalidate
                self.peer.track_side_task(
                    asyncio.get_event_loop().create_task(self._send_invalidation())
                )
                return
            super().restart()
        else:
            super().restart()

    async def _capture_target(self):
        from ..core.context import suspend_dependency_capture

        args = loads(self.message.argument_data)
        service_def = self.peer.hub.service_registry.require(self.message.service)
        method = service_def.method(self.message.method)
        with suspend_dependency_capture():  # RPC boundary: no cross-wire edges
            computed = await try_capture(lambda: method.fn(*args))
        if computed is None:
            raise RuntimeError(
                f"{self.message.service}.{self.message.method} is not a compute method "
                f"(nothing was captured)"
            )
        return computed

    def _on_computed_invalidated(self, computed) -> None:
        """Sync invalidation handler: unindex, unregister, push. Runs inside
        the invalidation (host-led cascade or the wave's eager apply)."""
        if self._fanout_nid is not None:
            fanout = getattr(self.peer.hub, "compute_fanout", None)
            if fanout is not None:
                fanout.unregister(self._fanout_nid, self.peer, self.call_id)
            self._fanout_nid = None
        self.peer.inbound_calls.pop(self.call_id, None)
        if self._invalidation_pushed:
            return  # the wave drain already batched this subscription
        # a HOST-LED invalidation (reshard fence, manual invalidate — not a
        # wave the publisher intercepted): a standing publish registration
        # must not outlive it — the plain invalidation below tells the edge
        # to re-read and re-arm, and a stale standing record would keep
        # publishing values for a subscription the client already replaced
        publisher = getattr(self.peer.hub, "value_publisher", None)
        if publisher is not None:
            publisher.drop_standing(self.peer, self.call_id)
        pushed = False
        if getattr(self.peer.hub, "coalesce_invalidations", True):
            self._invalidation_pushed = True
            version = computed.version.format() if computed is not None else None
            try:
                self.peer.outbox.post_invalidation(
                    self.call_id,
                    version,
                    cause=getattr(computed, "_invalidation_cause", None),
                    origin_ts=time.perf_counter(),
                )
            except RuntimeError:  # no running loop: no live link to push to
                pass
            else:
                pushed = True
        else:
            # per-key wire shape: the send awaits the channel — needs a task
            def _spawn():
                self.peer.track_side_task(
                    asyncio.get_event_loop().create_task(self._send_invalidation())
                )

            try:
                _spawn()
                pushed = True
            except RuntimeError:
                # invalidation applied from an off-loop thread: marshal the
                # spawn onto the peer's home loop (parity with the old
                # watch task's threadsafe wakeup)
                home = self.peer.outbox._home_loop
                if home is not None and not home.is_closed():
                    try:
                        home.call_soon_threadsafe(_spawn)
                        pushed = True
                    except RuntimeError:
                        pass  # loop closed: peer is gone
        if pushed and RECORDER.enabled:
            # server side of the fence, journaled AFTER the push was
            # actually enqueued — a swallowed no-loop failure must not read
            # as "client was notified" in explain() (the mask-drain path
            # notes its own in rpc/fanout.py)
            RECORDER.note(
                "client_fenced",
                key=repr(computed.input) if computed is not None else None,
                cause=getattr(computed, "_invalidation_cause", None),
                count=1,
                detail=f"call#{self.call_id} peer={self.peer.ref}",
            )

    async def _send_invalidation(self, max_attempts: int = 100) -> None:
        """Deliver this subscription's invalidation.

        Default path: POST into the peer's outbox coalescer — synchronous,
        no awaited channel write per subscription; the outbox flushes one
        ``$sys-c.invalidate_batch`` frame per drain tick (version-deduped)
        and itself rides out reconnects (pending entries survive a link
        flap). ``hub.coalesce_invalidations = False`` selects the original
        one-frame-per-key wire shape below, kept for wire compat and as the
        fan-out A/B baseline.

        Callers: the per-key send task the invalidation handler spawns, and
        ``restart()`` (a re-sent call means the client's state is unknown —
        re-push unconditionally; ``_invalidation_pushed`` never gates here,
        duplicate delivery is a client-side no-op)."""
        cause = getattr(self.computed, "_invalidation_cause", None)
        if getattr(self.peer.hub, "coalesce_invalidations", True):
            version = (
                self.computed.version.format() if self.computed is not None else None
            )
            self.peer.outbox.post_invalidation(
                self.call_id, version, cause=cause, origin_ts=time.perf_counter()
            )
            return
        headers = [("@t0", repr(time.perf_counter()))]
        if cause is not None:
            headers.append(("@cause", cause))
        message = RpcMessage(
            call_type_id=CALL_TYPE_COMPUTE,
            call_id=self.call_id,
            service=COMPUTE_SYSTEM_SERVICE,
            method="invalidate",
            argument_data=dumps([self.call_id]),
            headers=tuple(headers),
        )
        for _ in range(max_attempts):
            try:
                await self.peer.send(message)
                return
            except Exception:  # noqa: BLE001 — wait for the link to return
                ev = self.peer.connection_state.latest()
                if ev.value.is_connected:
                    await asyncio.sleep(0.05)
                else:
                    try:
                        await asyncio.wait_for(ev.when(lambda s: s.is_connected), 30.0)
                    except asyncio.TimeoutError:
                        return  # client is gone; it will resubscribe on return

    def on_completed(self) -> None:
        pass  # compute calls manage their own registration lifetime


async def _serve_recompute_batch(peer: "RpcPeer", message: RpcMessage) -> None:
    """Server side of ``$sys-c.recompute_batch`` (ISSUE 11 level 1): ONE
    inbound frame carries a whole fence-burst's per-key compute calls —
    ``[[call_id, service, method, args, publish, headers], ...]`` — and
    ONE ``recompute_batch_r`` frame answers every entry that captured
    cleanly. Each entry is dispatched as its own synthetic per-key message
    THROUGH the hub's inbound middleware chain, so the cluster shard guard
    (and any auth middleware) sees exactly the per-key wire shape: a
    stale-epoch entry is rejected with the carried map via the normal
    per-call ``$sys.error`` path and simply doesn't appear in the batch
    answer. The recompute itself still runs per key through the capture
    machinery — what this batches is the RPC/codec/loop-hop ENVELOPE."""
    from ..rpc.peer import _run_middlewares

    (entries,) = loads(message.argument_data)
    hub = peer.hub

    async def _serve_entry(entry):
        call_id = entry[0]
        service, method = entry[1], entry[2]
        args = entry[3]
        publish = bool(entry[4]) if len(entry) > 4 else False
        headers = (
            tuple((str(k), str(v)) for k, v in entry[5]) if len(entry) > 5 else ()
        )
        existing = peer.inbound_calls.get(call_id)
        if existing is not None:
            existing.restart()  # duplicate delivery after reconnect
            return None
        if call_id in peer._completed_inbound:
            return None  # already served and pruned
        sub_msg = RpcMessage(
            call_type_id=CALL_TYPE_COMPUTE,
            call_id=call_id,
            service=service,
            method=method,
            argument_data=dumps(list(args)),
            headers=headers,
        )
        served: dict = {}

        async def _terminal(msg: RpcMessage) -> None:
            inbound = RpcInboundComputeCall(peer, msg)
            result = await inbound.serve_inline(publish=publish)
            if result is not None:
                served["entry"] = result

        try:
            mws = hub.inbound_middlewares
            if mws:
                await _run_middlewares(mws, peer, sub_msg, _terminal)
            else:
                await _terminal(sub_msg)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — one entry's failure must
            # never poison its batch siblings: answer it per-key
            try:
                await peer.send(
                    RpcMessage(
                        CALL_TYPE_COMPUTE,
                        call_id,
                        SYSTEM_SERVICE,
                        "error",
                        dumps(ExceptionInfo.capture(e)),
                    )
                )
            except Exception:  # noqa: BLE001 — link died; reconnect re-serves
                pass
            return None
        return served.get("entry")

    # entries capture CONCURRENTLY (registry single-flight dedups shared
    # keys): one slow recompute must not head-of-line-block its batch
    # siblings — the per-key path ran each inbound call as its own task,
    # and the reply frame matches entries by call id, so order is free
    results = await asyncio.gather(
        *(_serve_entry(entry) for entry in entries), return_exceptions=True
    )
    ok_entries = []
    for result in results:
        if isinstance(result, asyncio.CancelledError):
            raise result
        if isinstance(result, BaseException):
            log.exception("recompute_batch entry failed", exc_info=result)
            continue
        if result is not None:
            ok_entries.append(result)
    if ok_entries:
        await peer.send(
            RpcMessage(
                call_type_id=CALL_TYPE_COMPUTE,
                call_id=0,
                service=COMPUTE_SYSTEM_SERVICE,
                method="recompute_batch_r",
                argument_data=dumps([ok_entries]),
            )
        )


def install_compute_call_type(rpc_hub: "RpcHub") -> None:
    """Register call type 1 + the $sys-c dispatcher on an RPC hub
    (≈ RpcComputeCallType.cs registration)."""
    rpc_hub.call_types.register(CALL_TYPE_COMPUTE, RpcOutboundComputeCall, RpcInboundComputeCall)

    def handle_compute_system(peer: "RpcPeer", message: RpcMessage) -> None:
        if message.method == "invalidate":
            (call_id,) = loads(message.argument_data)
            call = peer.outbound_calls.get(call_id)
            if isinstance(call, RpcOutboundComputeCall):
                t0 = message.header("@t0")
                call.set_invalidated(
                    cause=message.header("@cause"),
                    origin_ts=float(t0) if t0 else None,
                )
            else:
                # a publish-mode key's client call retires once the value
                # plane takes over (the edge invalidated its local node) —
                # a FALLBACK fence for it routes to the value-plane client
                vpc = getattr(peer.hub, "value_plane_client", None)
                if vpc is not None:
                    t0 = message.header("@t0")
                    vpc.on_block_fence(
                        peer, call_id, message.header("@cause"),
                        float(t0) if t0 else None,
                    )
        elif message.method == "invalidate_batch":
            # one frame, many subscriptions: [[call_id, version|None], ...].
            # Application is per-entry identical to a per-key invalidate —
            # invalidation is monotone, so the entry's version never gates
            # it (an entry for a version the client never saw still means
            # "your value is stale"; the PR-1 version-mismatch rule in
            # set_result covers the redelivered-result interaction, and a
            # dup/reordered batch finds the call already unregistered and
            # no-ops). The version rides for dedup at the sender and
            # diagnostics here.
            (entries,) = loads(message.argument_data)
            vpc = None
            for entry in entries:
                call = peer.outbound_calls.get(entry[0])
                if isinstance(call, RpcOutboundComputeCall):
                    # wire compat: pre-ISSUE-3 senders ship [cid, ver];
                    # current senders [cid, ver, cause, origin_ts]
                    call.set_invalidated(
                        cause=entry[2] if len(entry) > 2 else None,
                        origin_ts=entry[3] if len(entry) > 3 else None,
                    )
                else:
                    if vpc is None:
                        vpc = getattr(peer.hub, "value_plane_client", None)
                    if vpc is not None:
                        vpc.on_block_fence(
                            peer,
                            entry[0],
                            entry[2] if len(entry) > 2 else None,
                            entry[3] if len(entry) > 3 else None,
                        )
        elif message.method == "recompute_batch":
            # ISSUE 11 level 1: a whole fence-burst's re-reads in one
            # frame. Async (capture) — spawned, never awaited inline, the
            # same discipline as $sys-d: a slow recompute must not
            # head-of-line-block this link's invalidation frames
            task = asyncio.get_event_loop().create_task(
                _serve_recompute_batch(peer, message)
            )
            peer._diag_tasks.add(task)
            task.add_done_callback(peer._on_diag_done)
        elif message.method == "recompute_batch_r":
            (entries,) = loads(message.argument_data)
            for entry in entries:
                call = peer.outbound_calls.get(entry[0])
                if isinstance(call, RpcOutboundComputeCall):
                    call.set_batch_result(
                        entry[1], entry[2],
                        bool(entry[3]) if len(entry) > 3 else False,
                    )
        elif message.method == "value_block":
            # ISSUE 11 level 2: a wave's recomputed hot-set pushed as ONE
            # columnar frame — routed to whoever installed the value-plane
            # client on this hub (the EdgeNode)
            vpc = getattr(peer.hub, "value_plane_client", None)
            if vpc is not None:
                vpc.on_value_block(peer, message)

    rpc_hub.compute_system_handler = handle_compute_system
