"""Vectorized reads over RPC — the columnar path across the process boundary.

VERDICT r2 missing #1: the framework's flagship bulk-read shape (MemoTable +
``read_batch``) previously existed only in-process; a remote client got the
scalar compute-call path. This module carries it over the wire the same way
the reference carries scalar reads (PerformanceTest.cs "+ STJ serialization"
row; Client/Internal/RpcComputeSystemCalls.cs:13-26 for the push pattern):

- **server** (:class:`RemoteTableHost`): exposes named MemoTables over an
  ordinary RPC service — ``read_batch(name, ids)`` is ONE device gather and
  one ndarray-payload response — and pushes **per-table row fences**
  (``$sys-t.fence`` with the invalidated row ids + table version,
  fire-and-forget) to subscribed peers whenever rows invalidate. One
  subscription covers every row of a table: the per-call ``$sys-c`` pattern
  at table granularity.
- **client** (:class:`RemoteTable`): a local row cache (values + validity)
  fed by batched RPC reads; fences flip rows stale, so repeat reads are
  LOCAL gathers until the server actually invalidates. A fence that lands
  while a read is in flight wins over the in-flight response (per-row fence
  stamps), and a reconnect conservatively invalidates every cached row and
  resubscribes — fences dropped while the link was down can't strand stale
  rows.

Codec-keyed tables work remotely too (VERDICT r3 #4): ``read_keys`` carries
string/composite keys over the wire with the SERVER's codec authoritative —
the server interns unknown keys (``$tables.read_keys``), the client learns
the key→row assignments from responses and thereafter reads by row id
(local gathers until a fence lands). The reference's RPC carries arbitrary
argument lists for every call (Configuration/RpcByteArgumentSerializer.cs:
8-60); this is that capability at table granularity. A reconnect clears the
learned key map along with the row cache — a restarted server may intern
keys onto different rows, and only its codec is truth.
"""
from __future__ import annotations

import asyncio
import logging
import weakref
from typing import TYPE_CHECKING, Dict, Optional

import numpy as np

from ..rpc.message import TABLE_SYSTEM_SERVICE, RpcMessage
from ..utils.serialization import dumps, loads

if TYPE_CHECKING:
    from ..ops.memo_table import MemoTable
    from ..rpc.hub import RpcHub
    from ..rpc.peer import RpcPeer

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["RemoteTableHost", "RemoteTable", "TABLE_RPC_SERVICE"]

TABLE_RPC_SERVICE = "$tables"


from ..utils.serialization import deep_tuple as _deep_tuple


def _table_system(rpc_hub: "RpcHub") -> dict:
    """One composite ``$sys-t`` dispatcher per hub: a hub may HOST tables
    (subscribe messages from downstream peers) and CONSUME remote tables
    (fence messages from upstream) at the same time — two assignments to
    ``table_system_handler`` would silently drop one direction."""
    sys_state = getattr(rpc_hub, "_table_system", None)
    if sys_state is None:
        sys_state = rpc_hub._table_system = {"host": None, "tables": {}}

        def handle(peer: "RpcPeer", message: RpcMessage) -> None:
            if message.method == "subscribe":
                host = sys_state["host"]
                if host is not None:
                    host._handle_subscribe(peer, message)
                else:
                    log.warning("subscribe with no RemoteTableHost on this hub")
            elif message.method == "fence":
                name, version, ids = loads(message.argument_data)
                table = sys_state["tables"].get((getattr(peer, "ref", None), name))
                if table is not None:
                    table._apply_fence(version, ids)

        rpc_hub.table_system_handler = handle
    return sys_state


class RemoteTableHost:
    """Server side: named MemoTables served over RPC with fence push.

    ``expose(name, table)`` wires the table's ``on_invalidate`` to a
    ``$sys-t.fence`` push toward every subscribed peer. Subscriptions
    arrive as ``$sys-t.subscribe`` messages (transport-level, so the
    subscribing PEER is known — an ordinary service method never sees its
    caller); a peer whose push fails is dropped and will resubscribe on
    reconnect, where the client invalidates its whole cache anyway.
    """

    def __init__(self, rpc_hub: "RpcHub"):
        self.rpc_hub = rpc_hub
        self.tables: Dict[str, "MemoTable"] = {}
        # name → {id(peer): weakref(peer)} — weak so a dead server peer
        # never pins its connection state
        self._subs: Dict[str, Dict[int, "weakref.ref[RpcPeer]"]] = {}
        self._fence_tasks: set = set()  # the loop holds tasks weakly
        rpc_hub.add_service(TABLE_RPC_SERVICE, _TableRpcService(self))
        sys_state = _table_system(rpc_hub)
        if sys_state["host"] is not None:
            raise ValueError("this hub already has a RemoteTableHost")
        sys_state["host"] = self

    def expose(self, name: str, table: "MemoTable") -> "RemoteTableHost":
        if name in self.tables:
            raise ValueError(f"table {name!r} already exposed")
        self.tables[name] = table
        self._subs[name] = {}

        def on_invalidate(ids: np.ndarray) -> None:
            self._push_fence(name, table.version, np.asarray(ids, dtype=np.int32))

        table.on_invalidate.append(on_invalidate)

        def on_wave_invalidate(ids: np.ndarray) -> None:
            # device bursts mark rows stale through the wave path, which
            # keeps on_invalidate silent (the wave owns the cascade) — the
            # backend fires this hook instead, so burst-fenced rows reach
            # remote subscribers too (pre-coalescer they never did).
            # Deferred via call_soon: the hook runs INSIDE wave application
            # (backend contract: hooks must be cheap), and serializing a
            # wave-sized id payload there would stall the burst.
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return  # no loop → no live link; reconnect covers it
            loop.call_soon(
                self._push_fence, name, table.version,
                np.asarray(ids, dtype=np.int32),
            )

        table.on_wave_invalidate.append(on_wave_invalidate)
        return self

    def _require(self, name: str) -> "MemoTable":
        table = self.tables.get(name)
        if table is None:
            raise LookupError(f"no table {name!r} exposed; have {sorted(self.tables)}")
        return table

    def _handle_subscribe(self, peer: "RpcPeer", message: RpcMessage) -> None:
        (name,) = loads(message.argument_data)
        subs = self._subs.get(name)
        if subs is None:
            log.warning("subscribe for unknown table %r from %s", name, peer.ref)
            return
        subs[id(peer)] = weakref.ref(peer)

    def _push_fence(self, name: str, version: int, ids: np.ndarray) -> None:
        subs = self._subs.get(name, {})
        if not subs:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # wave applied outside any event loop (sync bench/test paths):
            # there is no live connection to push to from here — subscribers
            # recover via the reconnect invalidate-all contract
            return
        message = RpcMessage(
            call_type_id=0,
            call_id=0,
            service=TABLE_SYSTEM_SERVICE,
            method="fence",
            argument_data=dumps([name, version, ids]),
        )
        for key, ref in list(subs.items()):
            peer = ref()
            if peer is None:
                subs.pop(key, None)
                continue
            task = asyncio.ensure_future(self._send_fence(peer, message, subs, key))
            # the loop references tasks weakly: an unanchored fence push
            # could be collected mid-flight and silently lost
            self._fence_tasks.add(task)
            task.add_done_callback(self._fence_tasks.discard)

    async def _send_fence(self, peer, message, subs, key) -> None:
        try:
            await peer.send(message)
        except Exception:  # noqa: BLE001 — link down: drop the sub; the
            # client invalidates everything and resubscribes on reconnect,
            # so a fence lost here can never strand a stale row
            subs.pop(key, None)


class _TableRpcService:
    """The ordinary-RPC face of a RemoteTableHost (reads only; the fence
    channel is transport-level)."""

    def __init__(self, host: RemoteTableHost):
        self._host = host

    async def read_batch(self, name: str, ids: np.ndarray):
        table = self._host._require(name)
        values = np.asarray(table.read_batch(np.asarray(ids, dtype=np.int32)))
        return {"values": values, "version": table.version}

    async def read_keys(self, name: str, keys):
        """Keyed read with the SERVER's codec authoritative: unknown keys
        intern here (exactly like an in-process ``read_keys``); the response
        carries the assigned row ids so the client can fence-track them."""
        table = self._host._require(name)
        keys = [_deep_tuple(k) for k in keys]  # wire lists → hashable
        rows = table.encode_keys(keys)  # allocates: server is truth
        values = np.asarray(table.read_batch(rows))
        return {"rows": rows, "values": values, "version": table.version}

    async def table_info(self, name: str):
        table = self._host._require(name)
        return {
            "n_rows": table.n_rows,
            "row_shape": list(np.asarray(table.values).shape[1:]),
            "dtype": str(np.asarray(table.values).dtype),
            "version": table.version,
        }


class RemoteTable:
    """Client side: a fence-coherent local row cache over a served table.

    ``await read_batch(ids)`` returns the rows for ``ids``: valid rows come
    from the LOCAL cache (no wire traffic); stale rows fetch in ONE RPC
    batch. Rows turn stale when the server pushes a ``$sys-t`` fence for
    them — so a remote reader has the in-process contract: repeat reads are
    memoized until the row actually changes.
    """

    def __init__(self, rpc_hub: "RpcHub", peer_ref: str, name: str):
        self.rpc_hub = rpc_hub
        self.peer_ref = peer_ref
        self.name = name
        self.server_version = -1
        self.fences_seen = 0
        self.remote_reads = 0  # observability: RPC round trips paid
        self._values: Optional[np.ndarray] = None
        self._valid: Optional[np.ndarray] = None
        self._row_fence_stamp: Optional[np.ndarray] = None
        self._fence_counter = 0
        self._lock = asyncio.Lock()
        #: learned server key→row assignments (server codec authoritative;
        #: cleared on reconnect — a restarted server may re-intern)
        self._row_by_key: Dict = {}
        self._subscribed = False
        self._connects_seen = 0
        self._reconnect_task: Optional[asyncio.Task] = None
        self._fetch_lock = asyncio.Lock()
        tables = _table_system(rpc_hub)["tables"]
        key = (peer_ref, name)
        if key in tables:
            raise ValueError(f"RemoteTable for {key!r} already exists on this hub")
        tables[key] = self

    # ------------------------------------------------------------------ reads
    async def read_batch(self, ids) -> np.ndarray:
        ids_np = np.asarray(ids, dtype=np.int32)
        await self._ensure_ready()
        if not self._valid[ids_np].all():
            # single-flight: concurrent readers of the same stale rows
            # coalesce behind one RPC (re-check under the lock — the
            # previous holder may have fetched our rows already)
            async with self._fetch_lock:
                stale = ids_np[~self._valid[ids_np]]
                if stale.size:
                    await self._fetch(np.unique(stale))
        return self._values[ids_np]

    async def read_keys(self, keys) -> np.ndarray:
        """Keyed reads over the wire (string / composite keys): unknown keys
        resolve remotely in ONE batch (the server interns them — its codec
        is authoritative), known keys read like ``read_batch`` — a local
        gather unless a fence marked their rows stale."""
        await self._ensure_ready()
        norm = [_deep_tuple(k) for k in keys]
        rows = np.empty(len(keys), dtype=np.int64)
        # a reconnect mid-fetch clears the learned map (the server may have
        # re-interned), vaporizing keys outside the in-flight batch — retry
        # resolution instead of crashing (bounded: repeated drops give up)
        for _attempt in range(3):
            unknown = [j for j, k in enumerate(norm) if k not in self._row_by_key]
            if not unknown:
                break
            async with self._fetch_lock:
                still = [j for j in unknown if norm[j] not in self._row_by_key]
                if still:
                    # dedup while preserving one representative per key
                    uniq = list({norm[j]: None for j in still})
                    await self._fetch_keys(uniq)
        else:
            missing = [norm[j] for j in range(len(norm)) if norm[j] not in self._row_by_key]
            if missing:
                raise ConnectionError(
                    f"keyed resolution kept getting invalidated by reconnects: {missing[:3]}"
                )
        for j, k in enumerate(norm):
            rows[j] = self._row_by_key[k]
        ids_np = rows.astype(np.int32)
        if not self._valid[ids_np].all():
            async with self._fetch_lock:
                stale = ids_np[~self._valid[ids_np]]
                if stale.size:
                    await self._fetch(np.unique(stale))
        return self._values[ids_np]

    async def _fetch_keys(self, keys) -> None:
        fence_floor = self._fence_counter
        resp = await self.rpc_hub.call(
            TABLE_RPC_SERVICE, "read_keys", (self.name, list(keys)),
            peer_ref=self.peer_ref,
        )
        self.remote_reads += 1
        rows = np.asarray(resp["rows"], dtype=np.int32)
        self._values[rows] = resp["values"]
        for k, r in zip(keys, rows):
            self._row_by_key[_deep_tuple(k)] = int(r)
        self.server_version = max(self.server_version, resp["version"])
        # same in-flight-fence rule as _fetch: a fence stamped after this
        # read began wins — the row keeps the value but stays stale
        unfenced = self._row_fence_stamp[rows] <= fence_floor
        self._valid[rows[unfenced]] = True

    async def _ensure_ready(self) -> None:
        if self._subscribed:
            return
        async with self._lock:
            if self._subscribed:
                return
            peer = self.rpc_hub.client_peer(self.peer_ref)
            await peer.when_connected()
            # subscribe BEFORE the first read: a row invalidated after the
            # subscription lands as a fence; one invalidated before it is
            # covered because every row starts stale
            await peer.send(_subscribe_message(self.name))
            info = await self.rpc_hub.call(
                TABLE_RPC_SERVICE, "table_info", (self.name,), peer_ref=self.peer_ref
            )
            n = info["n_rows"]
            self._values = np.zeros((n, *info["row_shape"]), dtype=np.dtype(info["dtype"]))
            self._valid = np.zeros(n, dtype=bool)
            self._row_fence_stamp = np.full(n, -1, dtype=np.int64)
            self.server_version = info["version"]
            self._subscribed = True
            self._reconnect_task = asyncio.ensure_future(self._watch_reconnects(peer))

    async def _fetch(self, ids_np: np.ndarray) -> None:
        fence_floor = self._fence_counter
        resp = await self.rpc_hub.call(
            TABLE_RPC_SERVICE, "read_batch", (self.name, ids_np), peer_ref=self.peer_ref
        )
        self.remote_reads += 1
        self._values[ids_np] = resp["values"]
        self.server_version = max(self.server_version, resp["version"])
        # a fence that landed while this read was in flight WINS: those
        # rows keep the fetched value but stay stale, so the next read
        # refetches (the response was gathered before the invalidation).
        # <= : a row whose stamp EQUALS the floor was fenced before this
        # fetch began, so the response already reflects it — `<` would
        # leave such rows permanently stale (cache-missing forever)
        unfenced = self._row_fence_stamp[ids_np] <= fence_floor
        self._valid[ids_np[unfenced]] = True

    # ------------------------------------------------------------------ fences
    def _apply_fence(self, version: int, ids: Optional[np.ndarray]) -> None:
        self.fences_seen += 1
        if self._valid is None:
            return  # fence raced _ensure_ready; every row is stale anyway
        self._fence_counter += 1
        if ids is None:
            self._valid[:] = False
            self._row_fence_stamp[:] = self._fence_counter
        else:
            ids = np.asarray(ids, dtype=np.int32)
            self._valid[ids] = False
            self._row_fence_stamp[ids] = self._fence_counter
        self.server_version = max(self.server_version, version)

    async def _watch_reconnects(self, peer) -> None:
        """A reconnect means fences may have been dropped: conservatively
        invalidate every cached row and resubscribe (the server dropped our
        subscription on the failed push, or never knew the link died)."""
        ev = peer.connection_state.latest()
        was_connected = ev.value.is_connected
        while True:
            try:
                ev = await ev.when(lambda s: s.is_connected != was_connected)
            except asyncio.CancelledError:
                return
            was_connected = ev.value.is_connected
            if was_connected:
                self._apply_fence(self.server_version, None)
                # a restarted server may intern keys onto different rows;
                # its codec is the only truth — relearn from scratch
                self._row_by_key.clear()
                try:
                    await peer.send(_subscribe_message(self.name))
                except Exception:  # noqa: BLE001 — next flip retries
                    pass

    def dispose(self) -> None:
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
        tables = _table_system(self.rpc_hub)["tables"]
        key = (self.peer_ref, self.name)
        if tables.get(key) is self:
            tables.pop(key, None)


def _subscribe_message(name: str) -> RpcMessage:
    return RpcMessage(
        call_type_id=0,
        call_id=0,
        service=TABLE_SYSTEM_SERVICE,
        method="subscribe",
        argument_data=dumps([name]),
    )

