"""Client-side compute functions: cache-or-remote with invalidation binding.

Re-expression of src/Stl.Fusion/Client/Interception/ —
ClientComputeMethodFunction (:20-234), ClientComputed (:16-89) and the proxy
wiring (Internal/FusionProxies.cs). A client proxy's methods are REAL
compute methods on the client's own graph: results intern into the client
registry, participate in dependency capture (a client ComputedState can
depend on remote values), and invalidate when the server pushes
``$sys-c.invalidate`` — re-entering the local cascade.

Paths, mirroring the reference:
- REMOTE: send a compute call, bind the resulting ClientComputed to the
  call's invalidation future; if the result lands already-invalidated
  (server invalidated between result and subscription), retry ≤3
  (ClientComputeMethodFunction.cs:99-126);
- CACHED: if a client cache holds bytes for the key, return a cache-based
  computed IMMEDIATELY and race the true RPC in the background with
  dependency capture suppressed (:59-85); when the true result arrives,
  reuse the cached node if bytes match, else invalidate + replace
  (:128-151); ``when_synchronized()`` gates consumers that need confirmed
  values.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, Optional, Tuple

from ..core.computed import Computed
from ..core.context import ComputeContext, suspend_dependency_capture
from ..core.function import FunctionBase
from ..core.hub import FusionHub, default_hub
from ..core.inputs import ComputedInput
from ..core.options import ComputedOptions
from ..utils.ltag import LTag
from ..utils.result import Result
from ..utils.serialization import dumps, loads
from .cache import ClientComputedCache, RpcCacheKey
from .compute_call import ResultMissedError, RpcOutboundComputeCall

log = logging.getLogger("stl_fusion_tpu")

__all__ = ["ClientComputed", "ClientComputeMethodFunction", "FusionClient", "compute_client"]


# cluster/router.py FAILOVER_HEADER as a literal: client_function loads
# before (and without) the cluster package
_FAILOVER_HEADER = "@failover"


def _is_shard_moved(e: BaseException) -> bool:
    """Function-local cluster import: client_function loads before (and
    without) the cluster package; the check must never create the cycle."""
    try:
        from ..cluster.shard_map import ShardMovedError
    except ImportError:  # pragma: no cover — cluster ships with the package
        return False
    return isinstance(e, ShardMovedError)


class ClientComputeMethodInput(ComputedInput):
    __slots__ = ("function_ref", "method", "args")

    def __init__(self, function_ref: "ClientComputeMethodFunction", method: str, args: tuple):
        self.function_ref = function_ref
        self.method = method
        self.args = args
        self._hash = hash((id(function_ref), method, args))

    @property
    def function(self) -> "FunctionBase":
        return self.function_ref

    def cache_key(self) -> RpcCacheKey:
        return RpcCacheKey(self.function_ref.service, self.method, dumps(list(self.args)))

    def __eq__(self, other):
        return (
            type(other) is ClientComputeMethodInput
            and self.function_ref is other.function_ref
            and self.method == other.method
            and self.args == other.args
        )

    def __hash__(self):
        return self._hash

    def __repr__(self):
        return f"{self.function_ref.service}.{self.method}{self.args!r} (client)"


class ClientComputed(Computed):
    """A computed whose source of truth is a remote node."""

    __slots__ = ("call", "_synchronized")

    def __init__(self, input, version, options, call: Optional[RpcOutboundComputeCall]):
        super().__init__(input, version, options)
        self.call = call
        self._synchronized: Optional[asyncio.Future] = None
        if call is not None:
            self._bind_to_call(call)

    def _bind_to_call(self, call: RpcOutboundComputeCall) -> None:
        # sync callback, not a when_invalidated done_callback: the node
        # invalidates IN the dispatch that applied the $sys-c frame (a
        # done_callback defers by one loop hop per subscription — at
        # fan-out scale those hops dominated the staleness window). An
        # ALREADY-invalidated call (race with the result) keeps the
        # deferred path: binding happens before the node's output is set,
        # and an inline invalidate there would invert the output/invalidate
        # order the retry logic expects.
        if call.when_invalidated.done():
            call.when_invalidated.add_done_callback(
                lambda _f: self.invalidate(immediately=True)
            )
        else:
            call.invalidated_callbacks.append(
                lambda: self.invalidate(immediately=True)
            )
        self.on_invalidated(lambda _c: call.unregister())

    @property
    def invalidation_cause(self):
        """Cause id of the server-side wave/span that invalidated this node
        (carried in the ``$sys-c`` frame, ISSUE 3) — the client end of the
        cross-peer trace link; None while consistent or for cache-only
        nodes. Falls back to the locally-stamped cause (a client-side graph
        backend's wave) when no call delivered one."""
        call_cause = self.call.invalidation_cause if self.call is not None else None
        return call_cause or self._invalidation_cause

    @property
    def invalidation_origin_ts(self):
        """Server-side wave-apply timestamp the fence carried (perf_counter
        epoch, same-host trust caveat as ``fusion_e2e_delivery_ms``) —
        what lets the edge tier (ISSUE 8) measure fence → edge → session
        delivery end to end; None while consistent, for cache-only nodes,
        or when the server predates timestamp stamping."""
        return self.call.invalidation_origin_ts if self.call is not None else None

    # -- cache synchronization gate ---------------------------------------
    @property
    def is_synchronized(self) -> bool:
        return self.call is not None or self._synchronized is None or self._synchronized.done()

    def when_synchronized(self) -> asyncio.Future:
        if self._synchronized is None:
            self._synchronized = asyncio.get_event_loop().create_future()
            if self.call is not None:
                self._synchronized.set_result(None)
        return self._synchronized

    def _mark_synchronized(self) -> None:
        if self._synchronized is not None and not self._synchronized.done():
            self._synchronized.set_result(None)


class ClientComputeMethodFunction(FunctionBase):
    def __init__(
        self,
        hub: FusionHub,
        rpc_hub,
        service: str,
        peer_ref: Optional[str],
        cache: Optional[ClientComputedCache] = None,
        options: Optional[ComputedOptions] = None,
        cluster_routed: bool = False,
    ):
        super().__init__(hub, options or ComputedOptions.CLIENT_DEFAULT)
        self.rpc_hub = rpc_hub
        self.service = service
        self.peer_ref = peer_ref
        self.cache = cache
        #: True for the per-peer clients a RoutingComputeProxy caches: the
        #: peer was chosen by the hub's shard router, so calls stamp the
        #: router's @shard/@epoch headers even though peer_ref is fixed
        #: (cluster/router.py headers_for). A user-pinned CLIENT-mode proxy
        #: stays unstamped — pinning opts out of cluster routing.
        self.cluster_routed = cluster_routed

    # ------------------------------------------------------------------ compute
    async def compute(self, input: ClientComputeMethodInput, existing: Optional[Computed]) -> Computed:
        if self.cache is not None and existing is None:
            cached = self.cache.get(input.cache_key())
            if cached is not None:
                return self._cached_compute(input, cached)
        return await self._remote_compute(input, existing)

    def _cached_compute(self, input, cached_bytes: bytes) -> "ClientComputed":
        """Serve from cache NOW, confirm over RPC in the background."""
        version = self.hub.version_generator.next()
        computed = ClientComputed(input, version, self.options, call=None)
        computed.when_synchronized()  # arm the gate before consumers can ask
        computed.try_set_output(Result.ok(loads(cached_bytes)))
        self.hub.registry.register(computed)

        async def synchronize():
            with suspend_dependency_capture():
                try:
                    real = await self._remote_compute(input, None, register=False)
                except Exception:  # noqa: BLE001 — confirm failed; cache stays
                    log.exception("cache synchronization for %r failed", input)
                    return
            real_bytes = dumps(real._output.value_or_default)
            if real_bytes == cached_bytes and real.call is not None:
                # cached value confirmed: rebind THIS node to the live call
                computed.call = real.call
                computed._bind_to_call(real.call)
                computed._mark_synchronized()
            else:
                self.cache.set(input.cache_key(), real_bytes)
                self.hub.registry.register(real)
                computed._mark_synchronized()
                computed.invalidate(immediately=True)  # dependents re-pull the real node

        # owned by the rpc hub's side-task set: a cache-sync still in
        # flight when the hub stops is cancelled, not leaked (FL003)
        try:
            self.rpc_hub.side_tasks.spawn(synchronize())
        except RuntimeError:
            # hub mid-stop: serve the cached value unsynchronized — the
            # cache-hit path must never raise for a teardown race
            pass
        return computed

    async def _remote_compute(
        self, input, existing: Optional[Computed], register: bool = True
    ) -> "ClientComputed":
        tries = 0
        while True:
            tries += 1
            router = self.rpc_hub.call_router
            headers: tuple = ()
            if self.peer_ref is None and hasattr(router, "route"):
                # shard-map routing: the decision carries its @shard/@epoch
                # stamp (and @failover when the owner is unreachable)
                peer_ref, headers = router.route(self.service, input.method, input.args)
            else:
                peer_ref = self.peer_ref or router(self.service, input.method, input.args)
                if self.cluster_routed and hasattr(router, "headers_for"):
                    headers = router.headers_for(
                        self.service, input.method, input.args, peer_ref=peer_ref
                    )
            peer = self.rpc_hub.client_peer(peer_ref or "default")
            await peer.when_connected()
            call = RpcOutboundComputeCall(
                peer, self.service, input.method, input.args, headers=headers
            )
            try:
                value = await call.invoke()
                output = Result.ok(value)
            except asyncio.CancelledError:
                raise
            except ResultMissedError as e:
                # invalidation overtook the result (reconnect interleaving /
                # invalidate-only restart answer): just re-issue the call —
                # UNLESS the fence was a reshard: this peer no longer owns
                # the key, so re-issuing here would loop against a non-owner
                # (or park on a retired peer). Surface ShardMovedError so
                # the routing layer re-routes against the new map.
                cause = call.invalidation_cause
                if cause is not None and cause.startswith("reshard:"):
                    if self.peer_ref is None and tries <= 3:
                        continue  # we route per call: next try uses the new map
                    from ..cluster.shard_map import ShardMovedError

                    raise ShardMovedError(f"call fenced by {cause}") from e
                if tries <= 3:
                    continue
                output = Result.err(e)
            except Exception as e:  # noqa: BLE001 — errors are memoized
                if _is_shard_moved(e):
                    # never memoize a routing rejection: apply the carried
                    # map and either re-route (per-call routing) or hand the
                    # error to whoever owns the routing decision
                    if hasattr(router, "note_moved"):
                        router.note_moved(e)
                    if self.peer_ref is None and tries <= 3:
                        continue
                    raise
                output = Result.err(e)
            version = call.result_version or self.hub.version_generator.next()
            computed = ClientComputed(input, LTag(version), self.options, call)
            computed.try_set_output(output)
            # result arrived already invalidated ⇒ retry (≤3)
            if call.when_invalidated.done() and not output.has_error and tries <= 3:
                continue
            if register:
                self.hub.registry.register(computed)
            if self.cache is not None and not output.has_error:
                self.cache.set(input.cache_key(), dumps(value))
            if not output.has_error and any(k == _FAILOVER_HEADER for k, _ in headers):
                # a failover read is served by the REPLICA, whose $sys-c
                # subscription never sees the owner's writes — and an owner
                # that recovers without an epoch change fences nothing. So
                # the computed expires on the router's clock: the re-read
                # routes back to the recovered owner (or to the replica
                # again while the outage lasts, bounded thrash).
                ttl = getattr(router, "failover_ttl", 0.0)
                if ttl and ttl > 0:
                    self.hub.timeouts.schedule_invalidate(computed, ttl)
            return computed

    # ------------------------------------------------------------------ batch
    async def compute_batch(self, requests):
        """Batched remote compute (ISSUE 11 level 1): ``requests`` is a
        list of ``(method, args, publish)`` triples, all bound for THIS
        function's pinned peer; every entry becomes a real registered
        outbound compute call (reconnect re-send and redelivery dedup are
        the per-key machinery, untouched) but ONE
        ``$sys-c.recompute_batch`` frame carries them all and ONE
        ``recompute_batch_r`` frame answers — the RPC/codec/loop-hop
        envelope is paid once per burst instead of once per key.

        Returns one result per request, positionally: a registered
        :class:`ClientComputed` on success, or the Exception that entry
        died with (``ResultMissedError``/``ShardMovedError``/server
        errors) — the CALLER owns the per-key fallback ladder; this
        method never silently degrades, so fallbacks stay countable.
        Versions are the server computed's own LTags — oracle-exact with
        the per-key path."""
        if not requests:
            return []
        router = self.rpc_hub.call_router
        peer_ref = self.peer_ref or "default"
        peer = self.rpc_hub.client_peer(peer_ref)
        await peer.when_connected()
        from ..rpc.message import COMPUTE_SYSTEM_SERVICE, RpcMessage

        calls, entries = [], []
        for method, args, publish in requests:
            args = tuple(args)
            headers: tuple = ()
            if self.cluster_routed and hasattr(router, "headers_for"):
                headers = router.headers_for(
                    self.service, method, args, peer_ref=peer_ref
                )
            call = RpcOutboundComputeCall(
                peer, self.service, method, args, headers=headers
            )
            peer.outbound_calls[call.call_id] = call
            calls.append(call)
            entries.append(
                [
                    call.call_id,
                    self.service,
                    method,
                    list(args),
                    bool(publish),
                    [list(h) for h in headers],
                ]
            )
        message = RpcMessage(
            call_type_id=calls[0].call_type_id,
            call_id=0,
            service=COMPUTE_SYSTEM_SERVICE,
            method="recompute_batch",
            argument_data=dumps([entries]),
        )
        try:
            await peer.send(message)
        except Exception:  # noqa: BLE001 — not connected: the calls stay
            # registered and the reconnect re-send replays them per-key
            pass
        outcomes = await asyncio.gather(
            *(c.future for c in calls), return_exceptions=True
        )
        results = []
        for (method, args, _publish), call, outcome in zip(requests, calls, outcomes):
            if isinstance(outcome, BaseException):
                if _is_shard_moved(outcome) and hasattr(router, "note_moved"):
                    # apply the rejection's carried map BEFORE handing the
                    # error back (the per-key path's contract): the
                    # caller's retry re-routes against the new owner
                    # instead of spinning on the retired one
                    router.note_moved(outcome)
                call.unregister()
                results.append(outcome)
                continue
            if call.when_invalidated.done():
                # result arrived already invalidated: the per-key path's
                # bounded retry loop owns this shape — surface retriable
                results.append(
                    ResultMissedError(
                        f"batch entry {call.call_id} arrived already invalidated"
                    )
                )
                continue
            input = ClientComputeMethodInput(self, method, tuple(args))
            version = call.result_version or self.hub.version_generator.next()
            computed = ClientComputed(input, LTag(version), self.options, call)
            computed.try_set_output(Result.ok(outcome))
            self.hub.registry.register(computed)
            if self.cache is not None:
                self.cache.set(input.cache_key(), dumps(outcome))
            results.append(computed)
        return results


class FusionClient:
    """The client proxy: attribute access → client compute method.

    ≈ FusionProxies.NewClientProxy — an RPC client proxy wrapped by the
    client-compute interceptor."""

    def __init__(
        self,
        service: str,
        rpc_hub,
        fusion_hub: Optional[FusionHub] = None,
        peer_ref: Optional[str] = "default",
        cache: Optional[ClientComputedCache] = None,
        options: Optional[ComputedOptions] = None,
        cluster_routed: bool = False,
    ):
        self._function = ClientComputeMethodFunction(
            fusion_hub or default_hub(), rpc_hub, service, peer_ref, cache, options,
            cluster_routed=cluster_routed,
        )

    def capture_batch(self, requests):
        """Batched twin of ``capture(lambda: client.method(*args))`` × N
        (ISSUE 11): ``requests`` = ``[(method, args, publish), ...]`` →
        one ``recompute_batch`` frame; returns per-request
        ``ClientComputed`` or Exception (see
        :meth:`ClientComputeMethodFunction.compute_batch`)."""
        return self._function.compute_batch(requests)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        function = self._function

        async def call(*args):
            from ..core.context import OPT_INVALIDATE_BIT, get_current

            input = ClientComputeMethodInput(function, method, args)
            context = ComputeContext.current()
            used_by = None if context.call_options & OPT_INVALIDATE_BIT else get_current()
            return await function.invoke_and_strip(input, used_by, context)

        call.__name__ = method
        call.__fusion_remote_proxy__ = self  # invalidation replay is the owner's job
        return call


def compute_client(
    service: str,
    rpc_hub,
    fusion_hub: Optional[FusionHub] = None,
    peer_ref: Optional[str] = "default",
    cache: Optional[ClientComputedCache] = None,
) -> FusionClient:
    """Create an invalidation-aware client for a remote compute service."""
    return FusionClient(service, rpc_hub, fusion_hub, peer_ref, cache)
