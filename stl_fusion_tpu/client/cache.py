"""Client computed caches — boot-from-cache for remote results.

Re-expression of src/Stl.Fusion/Client/Caching/ + Rpc/Caching/RpcCacheKey.cs:
a persistent map ``(service, method, argument-bytes) → result-bytes`` that
survives restarts, letting a client render instantly from cached RPC results
and then synchronize (ClientComputedCache.cs:10-49). A version key flushes
the whole cache when the API generation changes.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = ["RpcCacheKey", "ClientComputedCache", "InMemoryClientComputedCache", "FileClientComputedCache"]


@dataclass(frozen=True)
class RpcCacheKey:
    service: str
    method: str
    arg_data: bytes

    def __repr__(self) -> str:
        return f"RpcCacheKey({self.service}.{self.method}, {len(self.arg_data)}B)"


class ClientComputedCache:
    """Abstract cache; values are serialized result bytes."""

    def get(self, key: RpcCacheKey) -> Optional[bytes]:
        raise NotImplementedError

    def set(self, key: RpcCacheKey, value: bytes) -> None:
        raise NotImplementedError

    def remove(self, key: RpcCacheKey) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class InMemoryClientComputedCache(ClientComputedCache):
    def __init__(self):
        self._map: Dict[RpcCacheKey, bytes] = {}

    def get(self, key):
        return self._map.get(key)

    def set(self, key, value):
        self._map[key] = value

    def remove(self, key):
        self._map.pop(key, None)

    def clear(self):
        self._map.clear()

    def __len__(self):
        return len(self._map)


class FileClientComputedCache(ClientComputedCache):
    """Flushing file-backed cache (≈ FlushingClientComputedCache): writes
    batch on a flush call or at a dirty-entry threshold; version-key flush
    on generation mismatch."""

    def __init__(self, path: str, version: str = "1", flush_threshold: int = 64):
        self.path = path
        self.version = version
        self.flush_threshold = flush_threshold
        self._map: Dict[Tuple[str, str, str], str] = {}
        self._dirty = 0
        self._lock = threading.Lock()
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                data = json.load(f)
            if data.get("version") != self.version:
                return  # generation changed: start empty (version-key flush)
            self._map = {tuple(k.split("\x00", 2)): v for k, v in data.get("entries", {}).items()}
        except Exception:  # noqa: BLE001 — corrupt cache: start empty
            self._map = {}

    def flush(self) -> None:
        with self._lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "version": self.version,
                        "entries": {"\x00".join(k): v for k, v in self._map.items()},
                    },
                    f,
                )
            os.replace(tmp, self.path)
            self._dirty = 0

    def _k(self, key: RpcCacheKey):
        return (key.service, key.method, key.arg_data.decode("utf-8", "replace"))

    def get(self, key):
        v = self._map.get(self._k(key))
        return v.encode("utf-8") if v is not None else None

    def set(self, key, value):
        self._map[self._k(key)] = value.decode("utf-8", "replace")
        self._dirty += 1
        if self._dirty >= self.flush_threshold:
            self.flush()

    def remove(self, key):
        self._map.pop(self._k(key), None)
        self._dirty += 1

    def clear(self):
        self._map.clear()
        self.flush()
