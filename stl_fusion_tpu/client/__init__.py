"""Fusion client layer — invalidation-aware caching RPC (SURVEY.md §2.5)."""
from .cache import (
    ClientComputedCache,
    FileClientComputedCache,
    InMemoryClientComputedCache,
    RpcCacheKey,
)
from .client_function import ClientComputed, ClientComputeMethodFunction, FusionClient, compute_client
from .compute_call import RpcInboundComputeCall, RpcOutboundComputeCall, install_compute_call_type
from .remote_table import TABLE_RPC_SERVICE, RemoteTable, RemoteTableHost
from .service_modes import RoutingComputeProxy, RpcServiceMode, add_fusion_service

__all__ = [
    "RoutingComputeProxy",
    "RpcServiceMode",
    "add_fusion_service",
    "ClientComputedCache",
    "FileClientComputedCache",
    "InMemoryClientComputedCache",
    "RpcCacheKey",
    "ClientComputed",
    "ClientComputeMethodFunction",
    "FusionClient",
    "compute_client",
    "RpcInboundComputeCall",
    "RpcOutboundComputeCall",
    "install_compute_call_type",
    "RemoteTable",
    "RemoteTableHost",
    "TABLE_RPC_SERVICE",
]
