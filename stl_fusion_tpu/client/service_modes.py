"""Service modes — how a compute service participates in distribution.

Re-expression of src/Stl.Rpc/RpcServiceMode.cs:3-11 and FusionBuilder's mode
dispatch (FusionBuilder.cs:222-320):

- LOCAL: plain local compute service (AddService).
- SERVER: local compute service, also exposed over RPC (AddServer).
- CLIENT: pure invalidation-aware RPC client proxy (AddClient).
- ROUTER: per-call routing proxy — the hub's call router picks a peer ref
  per (service, method, args); ``None``/empty routes to the local service
  (AddRouter; RpcRoutingInterceptor.cs:30-36).
- ROUTING_SERVER: SERVER whose locally-registered implementation is the
  real service, returning a routing proxy for callers (AddRoutingServer).
- SERVING_ROUTER: a router that is ITSELF exposed over RPC — a gateway
  node forwarding calls to the shard that owns them (AddServingRouter).

Remote legs are ``FusionClient``s, so routed results still memoize into
the caller's computed graph and invalidate on server push.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, Optional

from ..core.hub import FusionHub
from .cache import ClientComputedCache
from .client_function import FusionClient

__all__ = ["RpcServiceMode", "RoutingComputeProxy", "add_fusion_service"]


class RpcServiceMode(enum.Enum):
    LOCAL = "local"
    SERVER = "server"
    CLIENT = "client"
    ROUTER = "router"
    ROUTING_SERVER = "routing_server"
    SERVING_ROUTER = "serving_router"


class RoutingComputeProxy:
    """Per-call dispatch between a local service and per-peer fusion
    clients (≈ FusionProxies.NewRoutingProxy + RpcRoutingInterceptor)."""

    __rpc_dynamic__ = True  # methods materialize via __getattr__ when served

    def __init__(
        self,
        service_name: str,
        rpc_hub,
        fusion_hub: Optional[FusionHub] = None,
        local_service: Any = None,
        cache: Optional[ClientComputedCache] = None,
    ):
        self.service_name = service_name
        self.rpc_hub = rpc_hub
        self.fusion_hub = fusion_hub
        self.local_service = local_service
        self.cache = cache
        self._clients: Dict[str, FusionClient] = {}

    def client_for(self, peer_ref: str) -> FusionClient:
        client = self._clients.get(peer_ref)
        if client is None:
            client = FusionClient(
                self.service_name, self.rpc_hub, self.fusion_hub, peer_ref, self.cache,
                cluster_routed=True,
            )
            self._clients[peer_ref] = client
        return client

    def evict_peer(self, peer_ref: str) -> Optional[FusionClient]:
        """Drop (and return) the cached per-peer client. Pre-ISSUE-5 these
        were cached FOREVER: a peer that left the pool kept a live
        FusionClient (and its cache) routing into a dead socket. The
        cluster rebalancer calls this for every departed member; callers
        running a static pool can call it directly on membership edits."""
        return self._clients.pop(peer_ref, None)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        async def call(*args):
            attempts = 0
            while True:
                attempts += 1
                router = self.rpc_hub.call_router
                ref = router(self.service_name, method, args)
                if not ref:  # router says local (RpcClientInterceptor local fallback)
                    if self.local_service is None:
                        raise LookupError(
                            f"router returned local for {self.service_name}.{method} "
                            f"but no local service is registered"
                        )
                    return await getattr(self.local_service, method)(*args)
                try:
                    return await getattr(self.client_for(ref), method)(*args)
                except Exception as e:  # noqa: BLE001 — reshard retry only
                    # a shard-map rejection (the per-peer client already
                    # applied the carried map) or a retired peer: THIS is
                    # the layer that owns the routing decision, so re-route
                    # once against the current map. Static routers keep the
                    # historic raise-through behavior.
                    from ..cluster.shard_map import ShardMovedError

                    retriable = isinstance(e, ShardMovedError) or (
                        isinstance(e, ConnectionError) and hasattr(router, "route")
                    )
                    if not retriable or attempts >= 2:
                        raise

        call.__name__ = method
        call.__fusion_remote_proxy__ = self  # invalidation replay is the owner's job
        return call

    def __repr__(self) -> str:
        return f"RoutingComputeProxy({self.service_name}, local={self.local_service is not None})"


def add_fusion_service(
    mode: RpcServiceMode,
    service_name: str,
    rpc_hub,
    fusion_hub: Optional[FusionHub] = None,
    local_service: Any = None,
    peer_ref: str = "default",
    cache: Optional[ClientComputedCache] = None,
) -> Any:
    """Register a compute service in the given mode; returns the object
    callers should invoke (the local service, a client, or a router)."""
    if mode is RpcServiceMode.LOCAL:
        if local_service is None:
            raise ValueError("LOCAL mode needs local_service")
        return local_service
    if mode is RpcServiceMode.SERVER:
        if local_service is None:
            raise ValueError("SERVER mode needs local_service")
        rpc_hub.add_service(service_name, local_service)
        return local_service
    if mode is RpcServiceMode.CLIENT:
        return FusionClient(service_name, rpc_hub, fusion_hub, peer_ref, cache)
    if mode is RpcServiceMode.ROUTER:
        return RoutingComputeProxy(service_name, rpc_hub, fusion_hub, local_service, cache)
    if mode is RpcServiceMode.ROUTING_SERVER:
        if local_service is None:
            raise ValueError("ROUTING_SERVER mode needs local_service")
        rpc_hub.add_service(service_name, local_service)
        return RoutingComputeProxy(service_name, rpc_hub, fusion_hub, local_service, cache)
    if mode is RpcServiceMode.SERVING_ROUTER:
        router = RoutingComputeProxy(service_name, rpc_hub, fusion_hub, local_service, cache)
        rpc_hub.add_service(service_name, router)
        return router
    raise ValueError(f"unknown mode {mode}")
