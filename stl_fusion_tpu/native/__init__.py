"""Native runtime components (C++ via ctypes).

The performance-critical host-side pieces of the framework — where the
reference leans on the .NET runtime's optimized primitives, this build uses
C++ compiled on first use (g++ is in the image; no pip/pybind needed):

- ``graphpack``: the dual-ELL graph packer feeding the hybrid invalidation
  kernel (counting-sort degree bounding; ~10x the numpy path at 10M nodes).

Every native entry point has a numpy fallback — ``load_graphpack()``
returning None means "use the Python path", never a hard failure.
"""
from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading

log = logging.getLogger("stl_fusion_tpu")

__all__ = [
    "load_graphpack",
    "native_build_ell",
    "native_build_hybrid_tables",
    "native_topo_levels",
]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "graphpack.cpp")
_lock = threading.Lock()
_lib = None
_lib_failed = False


def _lib_path() -> str:
    # Content-keyed path: a source change produces a NEW .so path, so a
    # stale cached library can never be picked up, and we never need to
    # dlopen the same path twice (glibc dedupes dlopen by path, which would
    # silently return the old mapping instead of the rebuilt one).
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"_graphpack_{digest}.so")


def _compile(lib_path: str) -> bool:
    # no -march=native: a cached .so must run on any host this package is
    # copied to (counting sorts are memory-bound; vector ISA gains nothing)
    tmp = lib_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        result = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("graphpack native compile unavailable: %s", e)
        return False
    if result.returncode != 0:
        log.warning("graphpack native compile failed:\n%s", result.stderr[-2000:])
        return False
    os.replace(tmp, lib_path)  # atomic: concurrent processes race safely
    return True


def load_graphpack():
    """The ctypes lib, compiling on first use; None → use the numpy path."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        lib_path = _lib_path()
        if not os.path.exists(lib_path):
            if not _compile(lib_path):
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            log.warning("graphpack load failed: %s", e)
            _lib_failed = True
            return None
        lib.gp_build_hybrid.restype = ctypes.c_void_p
        lib.gp_build_hybrid.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
        lib.gp_n_tot.restype = ctypes.c_int64
        lib.gp_n_tot.argtypes = [ctypes.c_void_p]
        lib.gp_n_edges.restype = ctypes.c_int64
        lib.gp_n_edges.argtypes = [ctypes.c_void_p]
        lib.gp_fill.restype = ctypes.c_int32
        lib.gp_fill.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.gp_free.restype = None
        lib.gp_free.argtypes = [ctypes.c_void_p]
        lib.gp_topo_levels.restype = ctypes.c_int32
        lib.gp_topo_levels.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        ]
        lib.gp_build_ell.restype = ctypes.c_void_p
        lib.gp_build_ell.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int32,
        ]
        lib.gp_fill_out.restype = ctypes.c_int32
        lib.gp_fill_out.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32]
        _lib = lib
        return _lib


def native_topo_levels(in_src, n: int, k: int):
    """Kahn longest-path levels over a packed in-ELL table, or None → fallback.

    ``in_src`` is int32[(n+1), k] (row d's in-neighbors, entries >= n are
    pads); returns int32[n] with level[d] = 1 + max(level of in-neighbors).
    """
    import numpy as np

    lib = load_graphpack()
    if lib is None:
        return None
    in_src = np.ascontiguousarray(in_src, dtype=np.int32)
    level = np.empty(n, dtype=np.int32)
    rc = lib.gp_topo_levels(
        in_src.ctypes.data_as(ctypes.c_void_p), n, k,
        level.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        # A cycle is a hard invariant violation of the dependency DAG, not a
        # native-path miss: falling back would grind through the numpy
        # relaxation's full non-convergence loop before failing anyway.
        raise ValueError(f"dependency graph contains a cycle (gp_topo_levels rc={rc})")
    return level


def native_build_hybrid_tables(src, dst, n_nodes: int, k_in: int, k_out: int):
    """(in_src, out_dst, n_tot) via the native packer, or None → fallback."""
    import numpy as np

    lib = load_graphpack()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    handle = lib.gp_build_hybrid(
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        len(src), n_nodes, k_in, k_out,
    )
    try:
        n_tot = lib.gp_n_tot(handle)
        in_src = np.empty((n_tot + 1, k_in), dtype=np.int32)
        out_dst = np.empty((n_tot + 1, k_out), dtype=np.int32)
        rc = lib.gp_fill(
            handle,
            in_src.ctypes.data_as(ctypes.c_void_p),
            out_dst.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            log.error("graphpack degree bound violated (rc=%d); using numpy path", rc)
            return None
        return in_src, out_dst, int(n_tot)
    finally:
        lib.gp_free(handle)


def native_build_ell(src, dst, n_nodes: int, k: int):
    """(ell_dst[(n_tot+1), k], n_tot) bounding OUT-degree at k with virtual
    forwarding trees, via the native packer; None → numpy fallback."""
    import numpy as np

    lib = load_graphpack()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    handle = lib.gp_build_ell(
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        len(src), n_nodes, k, 1,
    )
    try:
        n_tot = lib.gp_n_tot(handle)
        ell_dst = np.empty((n_tot + 1, k), dtype=np.int32)
        rc = lib.gp_fill_out(handle, ell_dst.ctypes.data_as(ctypes.c_void_p), k)
        if rc != 0:
            log.error("graphpack ELL degree bound violated (rc=%d); using numpy path", rc)
            return None
        return ell_dst, int(n_tot)
    finally:
        lib.gp_free(handle)

