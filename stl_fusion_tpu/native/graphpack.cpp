// graphpack — native dual-ELL graph packer for the hybrid invalidation kernel.
//
// C++ counterpart of stl_fusion_tpu/ops/hybrid_wave.py::build_hybrid_graph
// (which is itself the TPU-shaped replacement for the reference's
// ComputedRegistry edge store — SURVEY §2.1). The Python/numpy path costs
// multiple argsort+unique passes over the 30M-edge list; this packer uses
// counting sorts (O(E+N) per round) and runs the whole two-phase
// degree-bounding + table-packing pipeline in a few hundred ms at 10M nodes.
//
// Pipeline (identical contract to the numpy path; virtual-id NUMBERING may
// differ, reachability semantics are equal — tests cross-check both):
//   phase 1: bound OUT-degree at k_out with virtual forwarding trees
//            (hub fan-out spread over log_k levels)
//   phase 2: bound IN-degree at k_in with virtual OR-collector trees
//   phase 3: pack in-ELL (n_tot+1, k_in) and out-ELL (n_tot+1, k_out),
//            pad slots pointing at the null row n_tot.
//
// C ABI (ctypes): gp_build_hybrid / gp_n_tot / gp_fill / gp_free.
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct EdgeList {
  std::vector<int64_t> src;
  std::vector<int64_t> dst;
};

struct Handle {
  int64_t n_tot = 0;
  int k_in = 0, k_out = 0;
  EdgeList edges;  // final bounded edge list
};

// Group edge indices by key (counting sort). offsets has n_keys+1 entries.
void group_by(const std::vector<int64_t>& key, int64_t n_keys,
              std::vector<int64_t>& order, std::vector<int64_t>& offsets) {
  offsets.assign(static_cast<size_t>(n_keys) + 1, 0);
  for (int64_t k : key) offsets[static_cast<size_t>(k) + 1]++;
  for (int64_t i = 0; i < n_keys; i++) offsets[i + 1] += offsets[i];
  order.resize(key.size());
  std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
  for (int64_t e = 0; e < static_cast<int64_t>(key.size()); e++)
    order[cursor[key[e]]++] = e;
}

// Bound the degree of one side by layered chunking under fresh virtual ids.
// bound_src=true bounds out-degree (forwarding trees): big source s with
// targets {d_i} emits final (virtual_j -> d_i) chunks and requeues
// (s -> virtual_j). bound_src=false bounds in-degree (collector trees): big
// dest x with sources {s_i} emits final (s_i -> collector_j) chunks and
// requeues (collector_j -> x).
void bound_degree(EdgeList& cur, int64_t& n_tot, int k, bool bound_src,
                  EdgeList& out_final) {
  std::vector<int64_t> order, offsets;
  while (!cur.src.empty()) {
    const std::vector<int64_t>& key = bound_src ? cur.src : cur.dst;
    // snapshot the id space: n_tot grows as chunks mint virtual ids, but
    // this round's groups (and offsets) only cover ids < n_before
    const int64_t n_before = n_tot;
    group_by(key, n_before, order, offsets);
    EdgeList next;
    for (int64_t g = 0; g < n_before; g++) {
      int64_t begin = offsets[g], end = offsets[g + 1];
      int64_t deg = end - begin;
      if (deg == 0) continue;
      if (deg <= k) {
        for (int64_t i = begin; i < end; i++) {
          int64_t e = order[i];
          out_final.src.push_back(cur.src[e]);
          out_final.dst.push_back(cur.dst[e]);
        }
        continue;
      }
      // chunk under virtual ids
      for (int64_t off = begin; off < end; off += k) {
        int64_t v = n_tot++;
        int64_t stop = off + k < end ? off + k : end;
        for (int64_t i = off; i < stop; i++) {
          int64_t e = order[i];
          if (bound_src) {
            out_final.src.push_back(v);          // virtual -> target (≤ k out)
            out_final.dst.push_back(cur.dst[e]);
          } else {
            out_final.src.push_back(cur.src[e]);  // source -> collector (≤ k in)
            out_final.dst.push_back(v);
          }
        }
        if (bound_src) {
          next.src.push_back(g);  // s -> virtual, rebound next round
          next.dst.push_back(v);
        } else {
          next.src.push_back(v);  // collector -> x, rebound next round
          next.dst.push_back(g);
        }
      }
    }
    cur = std::move(next);
  }
}

}  // namespace

extern "C" {

void* gp_build_hybrid(const int32_t* src, const int32_t* dst, int64_t m,
                      int64_t n_nodes, int k_in, int k_out) {
  Handle* h = new Handle();
  h->k_in = k_in;
  h->k_out = k_out;
  h->n_tot = n_nodes;

  EdgeList cur;
  cur.src.assign(src, src + m);
  cur.dst.assign(dst, dst + m);

  EdgeList after_out;
  bound_degree(cur, h->n_tot, k_out, /*bound_src=*/true, after_out);
  bound_degree(after_out, h->n_tot, k_in, /*bound_src=*/false, h->edges);
  return h;
}

int64_t gp_n_tot(void* handle) { return static_cast<Handle*>(handle)->n_tot; }

int64_t gp_n_edges(void* handle) {
  return static_cast<int64_t>(static_cast<Handle*>(handle)->edges.src.size());
}

// Fill caller-allocated tables: in_src[(n_tot+1)*k_in], out_dst[(n_tot+1)*k_out].
// Returns 0 on success, -1 if a degree bound was violated (internal bug).
int32_t gp_fill(void* handle, int32_t* in_src, int32_t* out_dst) {
  Handle* h = static_cast<Handle*>(handle);
  const int64_t n_tot = h->n_tot;
  const int64_t rows = n_tot + 1;
  const int32_t pad = static_cast<int32_t>(n_tot);
  std::fill(in_src, in_src + rows * h->k_in, pad);
  std::fill(out_dst, out_dst + rows * h->k_out, pad);

  std::vector<int32_t> in_slot(static_cast<size_t>(rows), 0);
  std::vector<int32_t> out_slot(static_cast<size_t>(rows), 0);
  const size_t m = h->edges.src.size();
  for (size_t e = 0; e < m; e++) {
    int64_t s = h->edges.src[e], d = h->edges.dst[e];
    if (out_slot[s] >= h->k_out || in_slot[d] >= h->k_in) return -1;
    out_dst[s * h->k_out + out_slot[s]++] = static_cast<int32_t>(d);
    in_src[d * h->k_in + in_slot[d]++] = static_cast<int32_t>(s);
  }
  return 0;
}

void gp_free(void* handle) { delete static_cast<Handle*>(handle); }

// Single-sided ELL: bound one side's degree at k (bound_src_flag != 0 →
// out-degree / forwarding trees, else in-degree / collector trees). The
// counterpart of ops/ell_wave.py::build_ell, whose numpy path costs
// repeated argsort+unique passes (~28 s at 10M nodes vs ~1 s here).
void* gp_build_ell(const int32_t* src, const int32_t* dst, int64_t m,
                   int64_t n_nodes, int k, int32_t bound_src_flag) {
  Handle* h = new Handle();
  h->k_in = k;
  h->k_out = k;
  h->n_tot = n_nodes;
  EdgeList cur;
  cur.src.assign(src, src + m);
  cur.dst.assign(dst, dst + m);
  bound_degree(cur, h->n_tot, k, bound_src_flag != 0, h->edges);
  return h;
}

// Fill a caller-allocated out-ELL table out_dst[(n_tot+1)*k]: row s holds
// its ≤ k targets, pad slots point at the null row n_tot.
int32_t gp_fill_out(void* handle, int32_t* out_dst, int32_t k) {
  Handle* h = static_cast<Handle*>(handle);
  const int64_t n_tot = h->n_tot;
  const int64_t rows = n_tot + 1;
  const int32_t pad = static_cast<int32_t>(n_tot);
  std::fill(out_dst, out_dst + rows * k, pad);
  std::vector<int32_t> slot(static_cast<size_t>(rows), 0);
  const size_t m = h->edges.src.size();
  for (size_t e = 0; e < m; e++) {
    int64_t s = h->edges.src[e];
    if (slot[s] >= k) return -1;
    out_dst[s * k + slot[s]++] = static_cast<int32_t>(h->edges.dst[e]);
  }
  return 0;
}

// Topological longest-path levels over a packed in-ELL table (Kahn sweep).
//
// in_src: int32[(n+1) * k] — row d's in-neighbors; entries >= n are pads.
// level (out): int32[n] — level[d] = 0 for source rows, else
//              1 + max(level of in-neighbors). Returns 0 on success,
//              -1 if the table contains a cycle (caller falls back).
//
// This feeds the topo-sweep invalidation kernel (ops/topo_wave.py): nodes
// renumbered in level order make the whole 32-wave cascade a single pass
// over the edge table instead of one full-graph gather per BFS level.
int32_t gp_topo_levels(const int32_t* in_src, int64_t n, int32_t k,
                       int32_t* level) {
  // out-adjacency via counting sort: edge (p -> d) per live entry
  std::vector<int64_t> off(static_cast<size_t>(n) + 1, 0);
  std::vector<int32_t> indeg(static_cast<size_t>(n), 0);
  for (int64_t d = 0; d < n; d++) {
    for (int32_t j = 0; j < k; j++) {
      int32_t p = in_src[d * k + j];
      if (p >= 0 && p < n) {
        off[static_cast<size_t>(p) + 1]++;
        indeg[d]++;
      }
    }
  }
  for (int64_t i = 0; i < n; i++) off[i + 1] += off[i];
  std::vector<int32_t> child(static_cast<size_t>(off[n]));
  {
    std::vector<int64_t> cursor(off.begin(), off.end() - 1);
    for (int64_t d = 0; d < n; d++)
      for (int32_t j = 0; j < k; j++) {
        int32_t p = in_src[d * k + j];
        if (p >= 0 && p < n) child[cursor[p]++] = static_cast<int32_t>(d);
      }
  }
  std::vector<int32_t> queue;
  queue.reserve(static_cast<size_t>(n));
  for (int64_t d = 0; d < n; d++) {
    level[d] = 0;
    if (indeg[d] == 0) queue.push_back(static_cast<int32_t>(d));
  }
  size_t head = 0;
  while (head < queue.size()) {
    int32_t u = queue[head++];
    int32_t lu = level[u];
    for (int64_t e = off[u]; e < off[u + 1]; e++) {
      int32_t d = child[e];
      if (level[d] < lu + 1) level[d] = lu + 1;
      if (--indeg[d] == 0) queue.push_back(d);
    }
  }
  return head == static_cast<size_t>(n) ? 0 : -1;
}

}  // extern "C"
