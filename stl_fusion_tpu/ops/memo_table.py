"""MemoTable — vectorized reactive memoization over a dense key space.

The TPU-first re-design of the reference's hot READ path
(Function.cs:56, ComputedRegistry.cs:57-70) for the case its benchmark
actually measures: millions of `users.Get(id)` reads over a dense integer
key space (tests/Stl.Fusion.Tests/PerformanceTest.cs:32-144). The scalar
`@compute_method` path keeps one Python node per key — the right shape for
heterogeneous dependency graphs, ~2.8 µs per memoized hit. When the key
space is dense and the read pattern is bulk, the TPU-native shape is
columnar instead:

- values live in device HBM as one array (pytree of arrays) with a row per
  key — the "registry" is a gather index, not a hash map;
- a batch of reads is ONE jitted gather (amortized cost: nanoseconds/read);
- consistency is a per-row validity bit: `invalidate(ids)` clears bits,
  the next read of a stale row triggers a vectorized recompute
  (`compute_fn(ids) -> rows`) and scatter — single-flight per refresh call,
  read-your-writes within a table;
- staleness bookkeeping is mirrored host-side (numpy) so `read_batch`
  never pays a device→host sync to decide whether to refresh (the axon
  relay costs ~64 ms per readback; a hot loop cannot afford that), while
  the packed device bitmask stays available to on-device consumers (wave
  kernels, masked matmuls).

Scalar-graph bridge: `on_invalidate` callbacks fire with the invalidated
ids, so a host `Computed` (e.g. an aggregate over the table) can subscribe
and cascade through the object graph; `changed` is an AsyncEvent stream of
table versions for reactive `ComputedState`-style consumers.
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ..diagnostics.flight_recorder import RECORDER
from ..utils.async_utils import AsyncEvent

__all__ = ["MemoTable"]

Ids = Union[Sequence[int], np.ndarray]


def _pad_repeat_pow2(ids_np: np.ndarray) -> np.ndarray:
    """Pow2-pad an id batch by repeating the first id — shape-quantizes the
    jitted kernels so varying batch sizes don't each compile a fresh device
    executable (set-style scatters are duplicate-safe)."""
    n = len(ids_np)
    if n == 0:
        return ids_np  # empty gathers/scatters stay empty (no [0] to repeat)
    width = 1
    while width < n:
        width <<= 1
    if width == n:
        return ids_np
    out = np.full(width, ids_np[0], dtype=np.int32)
    out[:n] = ids_np
    return out


class MemoTable:
    def __init__(
        self,
        n_rows: int,
        compute_fn: Callable[[np.ndarray], "np.ndarray"],
        row_shape: tuple = (),
        dtype=None,
        eager: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.n_rows = int(n_rows)
        self.compute_fn = compute_fn
        self.version = 0
        dtype = dtype or jnp.float32
        self._values = jnp.zeros((self.n_rows, *row_shape), dtype=dtype)
        # host-authoritative staleness (True = stale); device mirror is an
        # unpacked bool row mask (scatter of 0/1 is duplicate-safe, unlike a
        # packed-word RMW which loses bits when two ids share a word)
        self._stale_host = np.ones(self.n_rows, dtype=bool)
        self._stale_count = self.n_rows  # exact count, O(batch) to maintain
        self._valid_dev = jnp.zeros(self.n_rows, dtype=jnp.bool_)
        # True = the device mask lags _stale_host (wave application defers
        # the scatter — a 10M-row wave would upload 40 MB of ids through
        # the relay per burst); valid_mask/valid_bits materialize lazily
        self._valid_dev_dirty = False
        # small invalidate/refresh batches defer their device-mask scatter
        # here (applied in order at materialization): through a relay every
        # eager scatter is a ~100 ms dispatch, and a scalar write loop paid
        # one per op (r5 — the live bench's dominant non-burst phase)
        self._valid_pending: List[np.ndarray] = []
        self._valid_pending_n = 0
        self._packed_cache: Optional[tuple] = None  # (version, packed bits)
        self.on_invalidate: List[Callable[[np.ndarray], None]] = []
        #: fired BY THE GRAPH BACKEND with the local row ids a DEVICE WAVE
        #: marked stale (``_mark_stale_from_wave*`` itself stays silent —
        #: the wave owns the cascade; these hooks are for EXTERNAL
        #: observers such as the RPC fence push, which would otherwise
        #: never learn of burst-driven staleness). Only fired when
        #: non-empty, so unobserved tables pay nothing per wave.
        self.on_wave_invalidate: List[Callable[[np.ndarray], None]] = []
        #: fired with the refreshed ids after a vectorized recompute — the
        #: columnar analogue of a recompute's consistency restoration (the
        #: graph backend subscribes to clear device invalid bits in bulk)
        self.on_refresh: List[Callable[[np.ndarray], None]] = []
        #: optional DEVICE loader (set by TableBacking(device_batch=...)):
        #: jax-traceable ``(ids: int32[k] device, *args) -> rows`` — lets
        #: the graph backend refresh stale rows entirely on device
        #: (TpuGraphBackend.refresh_block_on_device), zero host traffic.
        #: ``device_loader_args()`` returns the loader's device-array state
        #: (threaded as runtime args, never closure constants).
        self.device_compute_fn = None
        self.device_loader_args = None
        #: optional key codec (set by TableBacking wiring): arbitrary
        #: hashable keys ⇄ dense rows — see read_keys/invalidate_keys
        self.key_codec = None
        #: declared key arity (set by TableBacking wiring): disambiguates a
        #: single-arg method whose KEY VALUES are tuples from a multi-arg
        #: method — a runtime isinstance(key, tuple) check cannot
        self.key_arity: Optional[int] = None
        self.changed: AsyncEvent = AsyncEvent(0)
        self._jit_cache = _kernels()  # shared: tables reuse one compile cache
        # /metrics exposure (ISSUE 3): stale backlog + version, summed over
        # live tables at scrape time — weak-registered, a collected table
        # drops out on its own; read_batch/invalidate never pay a registry hop
        from ..diagnostics.metrics import global_metrics

        global_metrics().register_collector(self, MemoTable._collect_metrics)
        if eager:
            self.refresh(np.arange(self.n_rows))

    def _collect_metrics(self) -> dict:
        return {
            "fusion_memo_tables": 1,
            "fusion_memo_rows": self.n_rows,
            "fusion_memo_stale_rows": self._stale_count,
            "fusion_memo_versions_total": self.version,
        }

    # ------------------------------------------------------------------ reads
    def read_batch(self, ids: Ids):
        """Values for ``ids`` (device array [k, ...]); refreshes stale rows
        first. The all-fresh fast path is one gather — no host↔device sync.

        ``ids`` may be a DEVICE array (jax): then the batch never crosses
        the host boundary — instead of gathering per-id staleness on the
        host (which would force a device→host readback), the ENTIRE current
        stale set (host-known, typically a handful of mutator-invalidated
        rows) is refreshed before the gather. Correct for any stale-set
        size, and the right trade when invalidations are sparse: the hot
        read loop stays pure async device dispatch, which is what lets
        batched reads pipeline at the kernel rate instead of the
        host-transfer rate."""
        if isinstance(ids, self._jax.Array):
            # device-resident ids (positive detection — every other
            # sequence type keeps the original np.asarray host contract):
            # refresh-all-stale, then one pure gather
            if self._stale_count:
                self.refresh(np.nonzero(self._stale_host)[0])
            return self._jit_cache["gather"](self._values, ids)
        ids_np = np.asarray(ids, dtype=np.int32)
        stale = self._stale_host[ids_np]
        if stale.any():
            self.refresh(np.unique(ids_np[stale]))
        k = len(ids_np)
        padded = _pad_repeat_pow2(ids_np)
        out = self._jit_cache["gather"](self._values, self._jnp.asarray(padded))
        return out if len(padded) == k else out[:k]

    def encode_keys(self, keys, allocate: bool = True) -> np.ndarray:
        """Dense row ids for arbitrary keys via the attached codec (a key is
        the call-args tuple, or the bare value for single-arg methods).
        ``allocate=False`` maps only already-interned keys (-1 otherwise)."""
        codec = self._require_codec()
        rows = np.empty(len(keys), dtype=np.int32)
        for j, k in enumerate(keys):
            args = self._key_to_args(k)
            row = codec.acquire(args) if allocate else codec.peek(args)
            rows[j] = -1 if row is None else row
        return rows

    def _key_to_args(self, k) -> tuple:
        """Canonical call-args tuple for a key, by DECLARED arity: a
        single-arg method's tuple-valued key must intern as ((1, 2),),
        never be mistaken for two args."""
        if self.key_arity == 1:
            return (k,)
        if self.key_arity is not None:
            if not isinstance(k, tuple) or len(k) != self.key_arity:
                raise TypeError(
                    f"key {k!r} does not match the method's arity "
                    f"({self.key_arity}): pass an args tuple"
                )
            return k
        return k if isinstance(k, tuple) else (k,)  # standalone-table heuristic

    def read_keys(self, keys):
        """``read_batch`` for codec-backed tables: keys are interned to rows
        (first read allocates), stale rows refresh through the service's
        batch method with the DECODED keys, one gather returns the values."""
        return self.read_batch(self.encode_keys(keys))

    def invalidate_keys(self, keys) -> None:
        """Mark the rows of already-interned ``keys`` stale (never-read keys
        have no row and are a no-op, not an allocation)."""
        rows = self.encode_keys(keys, allocate=False)
        rows = rows[rows >= 0]
        if rows.size:
            self.invalidate(rows)

    def _require_codec(self):
        if self.key_codec is None:
            raise TypeError(
                "this MemoTable has no key codec — declare "
                "TableBacking(keys=True) or read by integer row ids"
            )
        return self.key_codec

    @property
    def values(self):
        """The raw device value table (rows for stale ids may be outdated)."""
        return self._values

    MAX_VALID_PENDING = 4096  # total deferred ids before a full rebuild wins

    def _defer_valid(self, ids_np: np.ndarray, value: bool) -> None:
        """Queue a small device-mask update instead of dispatching it
        eagerly; past the budget the full lazy materialization is cheaper.
        The queue stores only the TOUCHED ids — at flush time the
        authoritative host staleness supplies each id's final value, so
        any number of deferred batches coalesce into ONE scatter."""
        if self._valid_dev_dirty:
            return  # full materialization already pending
        if self._valid_pending_n + len(ids_np) > self.MAX_VALID_PENDING:
            self._valid_dev_dirty = True
            self._valid_pending.clear()
            self._valid_pending_n = 0
        else:
            self._valid_pending.append(ids_np)
            self._valid_pending_n += len(ids_np)

    @property
    def valid_mask(self):
        """Per-row device validity mask (bool[n_rows]); materialized from
        the host-authoritative staleness if a wave application deferred it.
        Deferred small updates flush as ONE value-scatter: the final value
        of every touched id is just ``~stale_host[id]`` (host truth), so
        per-batch replay — and its one relay dispatch per batch — is
        unnecessary."""
        if self._valid_dev_dirty:
            self._valid_dev = self._jnp.asarray(~self._stale_host)
            self._valid_dev_dirty = False
            self._valid_pending.clear()
            self._valid_pending_n = 0
        elif self._valid_pending:
            ids = np.unique(np.concatenate(self._valid_pending))
            padded = _pad_repeat_pow2(ids)
            self._valid_dev = self._jit_cache["set_mask_vals"](
                self._valid_dev,
                self._jnp.asarray(padded),
                self._jnp.asarray(~self._stale_host[padded]),
            )
            self._valid_pending.clear()
            self._valid_pending_n = 0
        return self._valid_dev

    def valid_bits(self):
        """Packed per-row validity (uint32 lanes) for on-device bit-kernel
        consumers; packed on demand and cached per table version."""
        if self._packed_cache is None or self._packed_cache[0] != self.version:
            self._packed_cache = (self.version, self._jit_cache["pack"](self.valid_mask))
        return self._packed_cache[1]

    # ------------------------------------------------------------------ writes
    def refresh(self, ids: Ids) -> None:
        """Vectorized recompute + scatter for ``ids`` (marks them fresh).
        Ids are deduped: compute_fn sees each row once."""
        ids_np = np.unique(np.asarray(ids, dtype=np.int32))
        if ids_np.size == 0:
            return
        rows = self.compute_fn(ids_np)
        # pow2-pad by repeating the first row (duplicate scatter of the SAME
        # value is deterministic): refresh batch sizes vary per call, and a
        # fresh shape is a fresh device executable (~seconds via the relay)
        padded = _pad_repeat_pow2(ids_np)
        if len(padded) != len(ids_np):
            rows = np.asarray(rows)
            pad_rows = np.broadcast_to(
                rows[:1], (len(padded) - len(ids_np), *rows.shape[1:])
            )
            rows = np.concatenate([rows, pad_rows])
        jids = self._jnp.asarray(padded)
        self._values = self._jit_cache["scatter"](self._values, jids, self._jnp.asarray(rows))
        self._defer_valid(ids_np, True)  # dirty: lazy materialization covers it
        self._stale_count -= int(np.count_nonzero(self._stale_host[ids_np]))
        self._stale_host[ids_np] = False
        self._bump()
        if RECORDER.enabled:
            RECORDER.note(
                "table_refreshed",
                key=f"table:{id(self):x}",
                detail=f"{len(ids_np)} rows",
            )
        for handler in self.on_refresh:
            handler(ids_np)

    def invalidate(self, ids: Ids) -> None:
        """Mark rows stale; notifies subscribers (the cascade entry point).
        Ids are deduped: on_invalidate handlers see each row once."""
        ids_np = self._mark_stale(ids)
        if ids_np is not None:
            if RECORDER.enabled:
                # one event per CALL (never per row): host-led bulk marks
                # show up in the flight journal; wave-driven staleness is
                # already journaled by the backend's wave event
                RECORDER.note(
                    "table_invalidated",
                    key=f"table:{id(self):x}",
                    detail=f"{len(ids_np)} rows",
                )
            for handler in self.on_invalidate:
                handler(ids_np)

    def _mark_stale_from_wave_mask(self, rows_mask: np.ndarray) -> None:
        """Mask twin of :meth:`_mark_stale_from_wave` for lane bursts: the
        wave's newly-rows arrive as bool[rows] (possibly a prefix slice)
        and apply as two vectorized mask ops — no id materialization."""
        if not rows_mask.any():
            return
        sub = self._stale_host[: len(rows_mask)]
        self._stale_count += int(np.count_nonzero(rows_mask & ~sub))
        sub |= rows_mask
        self._valid_dev_dirty = True
        self._bump()

    def _mark_stale_from_wave(self, ids: Ids) -> None:
        """Device-wave application path (graph backend): mark rows stale
        WITHOUT firing ``on_invalidate`` — the wave already owns the cascade
        and the scalar-twin application (two-tier, graph/backend.py), so the
        table→scalar hook firing here would re-walk the whole wave in
        per-row Python. The device mask update is DEFERRED (dirty flag;
        wave ids are already unique, and a 10M-row id scatter would upload
        40 MB through the relay per burst). ``changed`` still advances."""
        ids_np = np.asarray(ids, dtype=np.int32)
        if ids_np.size == 0:
            return
        self._stale_count += int(np.count_nonzero(~self._stale_host[ids_np]))
        self._stale_host[ids_np] = True
        self._valid_dev_dirty = True
        self._bump()

    def _mark_stale(self, ids: Ids) -> Optional[np.ndarray]:
        """Shared staleness bookkeeping; returns the deduped ids (None when
        empty) so :meth:`invalidate` can notify with exactly what changed."""
        ids_np = np.unique(np.asarray(ids, dtype=np.int32))
        if ids_np.size == 0:
            return None
        self._stale_count += int(np.count_nonzero(~self._stale_host[ids_np]))
        self._stale_host[ids_np] = True
        self._defer_valid(ids_np, False)
        self._bump()
        return ids_np

    def invalidate_all(self) -> None:
        self._stale_host[:] = True
        self._stale_count = self.n_rows
        self._valid_dev = self._jnp.zeros_like(self._valid_dev)
        self._valid_dev_dirty = False
        self._valid_pending.clear()
        self._valid_pending_n = 0
        self._bump()
        if self.on_invalidate:
            all_ids = np.arange(self.n_rows, dtype=np.int32)
            for handler in self.on_invalidate:
                handler(all_ids)

    def _bump(self) -> None:
        self.version += 1
        self.changed = self.changed.create_next(self.version)

    # ------------------------------------------------------------------ checkpoint
    def export_state(self) -> dict:
        """Snapshot of the columnar state (values + per-row validity +
        version) for checkpoint/resume — the restart-surviving analogue of
        the reference's persistent client cache
        (Client/Caching/ClientComputedCache.cs:35-49)."""
        return {
            "values": np.asarray(self._values),
            "valid": (~self._stale_host).copy(),
            "version": int(self.version),
        }

    def import_state(self, state: dict) -> None:
        """Restore :meth:`export_state` output: valid rows read as warm
        hits immediately; stale rows refresh on first touch. Invalidation
        wiring (on_invalidate, codec) is the LIVE table's — import only
        replaces the row data, so post-restore invalidations propagate
        exactly like pre-snapshot ones."""
        values = np.asarray(state["values"])
        if values.shape != tuple(np.asarray(self._values).shape):
            raise ValueError(
                f"checkpoint shape {values.shape} != table shape "
                f"{tuple(np.asarray(self._values).shape)}"
            )
        valid = np.asarray(state["valid"], dtype=bool)
        self._values = self._jnp.asarray(values)
        self._stale_host = ~valid
        self._stale_count = int((~valid).sum())
        self._valid_dev = self._jnp.asarray(valid)
        self._valid_dev_dirty = False
        self._valid_pending.clear()
        self._valid_pending_n = 0
        self._packed_cache = None
        self.version = int(state["version"])
        self._bump()

    # ------------------------------------------------------------------ misc
    def stale_count(self) -> int:
        return self._stale_count

    def __repr__(self) -> str:
        return f"MemoTable({self.n_rows} rows, {self.stale_count()} stale, v{self.version})"


@functools.lru_cache(maxsize=1)
def _kernels():
    """Module-level jitted kernels: per-instance closures would give every
    MemoTable its own compile cache and recompile identical programs."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gather(values, ids):
        return values[ids]

    @jax.jit
    def scatter(values, ids, rows):
        return values.at[ids].set(rows)

    from functools import partial

    @partial(jax.jit, static_argnums=2)
    def set_mask(mask, ids, on):
        return mask.at[ids].set(on)

    @jax.jit
    def set_mask_vals(mask, ids, vals):
        return mask.at[ids].set(vals)

    from .bitops import pack_bool_bits_jit

    pack = pack_bool_bits_jit()  # shared wrapper: one trace cache repo-wide

    return {
        "gather": gather, "scatter": scatter, "set_mask": set_mask,
        "set_mask_vals": set_mask_vals, "pack": pack,
    }
