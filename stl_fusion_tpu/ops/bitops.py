"""Shared bit-pack primitives for the relay-thin transfer paths.

One definition of the little-endian bool→uint32 pack that burst epilogues,
overflow readbacks, and table validity bits all use (three modules had
drifted their own copies of it); the host-side twin lives in
graph/device_graph.py::_pack_mask_host next to its unpack kernel.
"""
from __future__ import annotations

import functools

__all__ = ["fused_pair_scatter", "pack_bool_bits", "pack_bool_bits_jit"]


def pack_bool_bits(mask):
    """bool[n] → uint32[ceil(n/32)] little-endian pack (traceable — use
    inside larger jitted programs; ships 1 bit/node through the per-byte-
    charged relay instead of 1 byte)."""
    import jax.numpy as jnp

    n = mask.shape[0]
    pad = (-n) % 32
    m = jnp.pad(mask, (0, pad)).reshape(-1, 32).astype(jnp.uint32)
    return (m << jnp.arange(32, dtype=jnp.uint32)).sum(axis=1, dtype=jnp.uint32)


@functools.lru_cache(maxsize=1)
def pack_bool_bits_jit():
    """Standalone jitted pack for eager callers."""
    import jax

    return jax.jit(pack_bool_bits)


@functools.lru_cache(maxsize=1)
def fused_pair_scatter():
    """One jitted row scatter updating a mirror's paired tables (ids +
    epochs): half the programs (and relay compiles) of two eager scatters,
    cached per (table shapes × width bucket) by jit itself. Shared by the
    single-chip topo/lat mirrors and the packed mesh mirror."""
    import jax

    @jax.jit
    def scat(t1, t2, rows, v1, v2):
        return t1.at[rows].set(v1), t2.at[rows].set(v2)

    return scat
