"""Shared bit-pack primitives for the relay-thin transfer paths.

One definition of the little-endian bool→uint32 pack that burst epilogues,
overflow readbacks, and table validity bits all use (three modules had
drifted their own copies of it); the host-side twin lives in
graph/device_graph.py::_pack_mask_host next to its unpack kernel.
"""
from __future__ import annotations

import functools

__all__ = [
    "fused_pair_scatter",
    "fused_quad_scatter",
    "pack_bool_bits",
    "pack_bool_bits_jit",
]


def pack_bool_bits(mask):
    """bool[n] → uint32[ceil(n/32)] little-endian pack (traceable — use
    inside larger jitted programs; ships 1 bit/node through the per-byte-
    charged relay instead of 1 byte)."""
    import jax.numpy as jnp

    n = mask.shape[0]
    pad = (-n) % 32
    m = jnp.pad(mask, (0, pad)).reshape(-1, 32).astype(jnp.uint32)
    return (m << jnp.arange(32, dtype=jnp.uint32)).sum(axis=1, dtype=jnp.uint32)


@functools.lru_cache(maxsize=1)
def pack_bool_bits_jit():
    """Standalone jitted pack for eager callers."""
    import jax

    return jax.jit(pack_bool_bits)


@functools.lru_cache(maxsize=1)
def fused_pair_scatter():
    """One jitted row scatter updating a mirror's paired tables (ids +
    epochs): half the programs (and relay compiles) of two eager scatters,
    cached per (table shapes × width bucket) by jit itself. Shared by the
    single-chip topo/lat mirrors and the packed mesh mirror."""
    import jax

    @jax.jit
    def scat(t1, t2, rows, v1, v2):
        return t1.at[rows].set(v1), t2.at[rows].set(v2)

    return scat


@functools.lru_cache(maxsize=1)
def fused_quad_scatter():
    """One jitted row scatter updating TWO paired-table mirrors at once
    (topo in-rows + lat out-rows of a patch application): through a relay
    every dispatch costs ~a round trip, and a churn patch touching both
    mirrors paid two — the dominant share of ``mirror_patch_ms`` (BENCH_r05:
    1090.7 ms for ~11k edges, nearly all of it dispatch, not numpy). The
    row batches are independent scatters; fusing them is purely a dispatch-
    count change."""
    import jax

    @jax.jit
    def scat(a1, a2, rows_a, va1, va2, b1, b2, rows_b, vb1, vb2):
        return (
            a1.at[rows_a].set(va1),
            a2.at[rows_a].set(va2),
            b1.at[rows_b].set(vb1),
            b2.at[rows_b].set(vb2),
        )

    return scat
