"""Hybrid 32-wave kernel: dense pull for big levels, sparse pull for the tail.

Why: the pure pull kernel (pull_wave.py) costs O(n·k) gathers EVERY level.
Measured on the bench DAG class, a full cascade runs ~6 wide levels and then
a long tail of near-empty ones — half the levels carry <0.1% of the work but
each still pays the full-graph gather. This kernel switches per level:

- **dense level** (frontier words > tail_cap): one `frontier[eff_in]`
  gather over all rows — the pull kernel, but with the epoch-liveness test
  FOLDED into the index table once per batch (`eff_in` redirects dead edges
  to the null row), removing the per-level `live` load and select.
- **sparse level** (≤ tail_cap active words): the next frontier can only
  appear on out-neighbors of active nodes, so: gather the active rows'
  out-slots (candidates), pull each candidate's in-row, OR, and scatter the
  new words back. Cost O(active · (k_out + k_in)) instead of O(n·k).
  Scatters use plain `set`: duplicate candidates compute identical values
  (a pull depends only on the candidate), so drops are benign.

Graph form: ONE augmented node space shared by both directions.
`build_hybrid_graph` first bounds out-degree at k_out with virtual
forwarding trees (hubs fan out over log_{k_out} levels — build_ell), then
bounds in-degree at k_in with virtual OR-collector trees (symmetric pass on
the dst side), then packs in-ELL and out-ELL from the SAME final edge list
— so dense and sparse levels traverse the identical graph and can alternate
freely (a hub firing late re-widens the frontier; the level switch handles
it). Reference semantics preserved: versioned edges (per-slot epoch vs row
epoch), invalidation idempotent/monotone (Computed.cs:162-230).
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Tuple

import numpy as np

from .ell_wave import build_ell

__all__ = [
    "HybridGraph",
    "HybridGraphArrays",
    "HybridState",
    "build_hybrid_graph",
    "hybrid_graph_arrays",
    "hybrid_init_state",
    "build_hybrid_wave32",
]


class HybridGraph(NamedTuple):
    """Host-built dual-ELL graph over one augmented node space."""

    in_src: np.ndarray  # int32[n_tot+1, k_in] — row d's in-neighbors; pad n_tot
    in_epoch: np.ndarray  # int32[n_tot+1, k_in] — captured epochs; pad -1
    out_dst: np.ndarray  # int32[n_tot+1, k_out] — row s's out-neighbors; pad n_tot
    is_real: np.ndarray  # bool[n_tot+1]
    n_real: int
    n_tot: int
    k_in: int
    k_out: int


def _bound_in_degree(
    src: np.ndarray, dst: np.ndarray, n_start: int, k_in: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Layered in-collector construction: while any dst row exceeds k_in,
    chunk its in-edges under fresh virtual OR-collectors (in-degree ≤ k_in,
    out-degree 1). Mirrors build_ell's out-side loop, but keeps the edge
    LIST so both ELLs can pack from the final graph."""
    next_id = n_start
    cur_src, cur_dst = src.astype(np.int64), dst.astype(np.int64)
    final_src: List[np.ndarray] = []
    final_dst: List[np.ndarray] = []
    while len(cur_dst):
        order = np.argsort(cur_dst, kind="stable")
        s, d = cur_src[order], cur_dst[order]
        uniq, starts, counts = np.unique(d, return_index=True, return_counts=True)
        rank = np.arange(len(d)) - np.repeat(starts, counts)
        deg = np.repeat(counts, counts)
        small = deg <= k_in
        final_src.append(s[small])
        final_dst.append(d[small])
        bs, bd, brank = s[~small], d[~small], rank[~small]
        if len(bs) == 0:
            break
        chunk = brank // k_in
        key = bd * (chunk.max() + 1) + chunk
        _, grp_first, grp_inv = np.unique(key, return_index=True, return_inverse=True)
        n_virtual = len(grp_first)
        virtual_ids = next_id + np.arange(n_virtual)
        next_id += n_virtual
        # source → collector (≤ k_in per collector by chunking)
        final_src.append(bs)
        final_dst.append(virtual_ids[grp_inv])
        # next round: collector → original dst (collectors may still exceed k_in)
        cur_src = virtual_ids
        cur_dst = bd[grp_first]
    return np.concatenate(final_src), np.concatenate(final_dst), next_id


def build_hybrid_graph(
    src: np.ndarray,
    dst: np.ndarray,
    n_nodes: int,
    k_in: int = 4,
    k_out: int = 8,
    use_native: bool = True,
) -> HybridGraph:
    if use_native:
        from ..native import native_build_hybrid_tables

        tables = native_build_hybrid_tables(src, dst, n_nodes, k_in, k_out)
        if tables is not None:
            in_src, out_dst, n_tot = tables
            in_epoch = np.where(in_src < n_tot, 0, -1).astype(np.int32)
            is_real = np.zeros(n_tot + 1, dtype=bool)
            is_real[:n_nodes] = True
            return HybridGraph(in_src, in_epoch, out_dst, is_real, n_nodes, n_tot, k_in, k_out)

    # numpy fallback path
    # pass 1: bound out-degree with forwarding trees (build_ell's loop);
    # its augmented edge list is (row → ell_dst slot) pairs
    out_ell = build_ell(src, dst, n_nodes, k=k_out)
    rows = np.repeat(np.arange(out_ell.n_tot + 1), out_ell.k)
    targets = out_ell.ell_dst.reshape(-1).astype(np.int64)
    valid = targets < out_ell.n_tot
    aug_src, aug_dst = rows[valid], targets[valid]

    # pass 2: bound in-degree with OR-collector trees on the same list
    aug_src, aug_dst, n_tot = _bound_in_degree(aug_src, aug_dst, out_ell.n_tot, k_in)

    def pack(rows_of: np.ndarray, vals_of: np.ndarray, k: int) -> np.ndarray:
        table = np.full((n_tot + 1, k), n_tot, dtype=np.int32)
        order = np.argsort(rows_of, kind="stable")
        r, v = rows_of[order], vals_of[order]
        uniq, starts, counts = np.unique(r, return_index=True, return_counts=True)
        slot = np.arange(len(r)) - np.repeat(starts, counts)
        assert slot.max() < k if len(slot) else True, "degree bound failed"
        table[r, slot] = v
        return table

    in_src = pack(aug_dst, aug_src, k_in)
    out_dst = pack(aug_src, aug_dst, k_out)
    in_epoch = np.where(in_src < n_tot, 0, -1).astype(np.int32)
    is_real = np.zeros(n_tot + 1, dtype=bool)
    is_real[:n_nodes] = True
    return HybridGraph(in_src, in_epoch, out_dst, is_real, n_nodes, n_tot, k_in, k_out)


class HybridGraphArrays(NamedTuple):
    in_src: "object"
    in_epoch: "object"
    out_dst: "object"
    is_real: "object"


class HybridState(NamedTuple):
    node_epoch: "object"  # int32[n_tot+1]
    invalid_bits: "object"  # int32[n_tot+1]


def hybrid_graph_arrays(graph: HybridGraph) -> HybridGraphArrays:
    import jax.numpy as jnp

    return HybridGraphArrays(
        in_src=jnp.asarray(graph.in_src),
        in_epoch=jnp.asarray(graph.in_epoch),
        out_dst=jnp.asarray(graph.out_dst),
        is_real=jnp.asarray(graph.is_real),
    )


def hybrid_init_state(n_tot: int) -> HybridState:
    import jax.numpy as jnp

    return HybridState(
        jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2),
        jnp.zeros(n_tot + 1, dtype=jnp.int32),
    )


def _hybrid_wave32_impl(tail_cap: int, garrays: HybridGraphArrays, seed_bits, state: HybridState):
    import jax.numpy as jnp
    from jax import lax

    in_src, in_epoch, out_dst, is_real = garrays
    n_tot = in_src.shape[0] - 1
    k_in = in_src.shape[1]
    k_out = out_dst.shape[1]

    node_epoch, invalid = state.node_epoch, state.invalid_bits
    invalid_before = invalid
    # fold liveness into the index table once per batch: dead edges (epoch
    # mismatch) point at the null row, whose frontier word is always 0
    eff_in = jnp.where(in_epoch == node_epoch[:, None], in_src, n_tot)

    frontier = (seed_bits & ~invalid).at[n_tot].set(0)
    invalid = invalid | frontier

    def or_fold(mat):
        acc = mat[:, 0]
        for j in range(1, mat.shape[1]):
            acc = acc | mat[:, j]
        return acc

    def dense_level(frontier, invalid):
        fire = or_fold(frontier[eff_in])
        fire = (fire & ~invalid).at[n_tot].set(0)
        return fire, invalid | fire

    def sparse_level(frontier, invalid):
        (active,) = jnp.nonzero(frontier, size=tail_cap, fill_value=n_tot)
        cand = out_dst[active].reshape(-1)  # (tail_cap * k_out,)
        fire = or_fold(frontier[eff_in[cand]])
        fire = fire & ~invalid[cand]
        fire = jnp.where(cand < n_tot, fire, 0)
        # duplicate candidates carry identical values → set-with-drop is safe
        invalid = invalid.at[cand].set(invalid[cand] | fire, mode="drop")
        frontier = jnp.zeros_like(frontier).at[cand].set(fire, mode="drop")
        return frontier, invalid

    def cond(carry):
        _f, _inv, go = carry
        return go

    def body(carry):
        frontier, invalid, _go = carry
        n_active = (frontier != 0).sum(dtype=jnp.int32)
        frontier, invalid = lax.cond(
            n_active <= tail_cap, sparse_level, dense_level, frontier, invalid
        )
        return frontier, invalid, (frontier != 0).any()

    _f, invalid, _go = lax.while_loop(cond, body, (frontier, invalid, (frontier != 0).any()))
    newly = lax.population_count(jnp.where(is_real, invalid & ~invalid_before, 0))
    return HybridState(node_epoch, invalid), newly.sum(dtype=jnp.int32)


@functools.lru_cache(maxsize=4)
def hybrid_wave32_step(tail_cap: int = 8192):
    """Jitted hybrid kernel: ``step(garrays, seed_bits, state)``; graph
    arrays are runtime args (see pull_wave.py on compile payloads)."""
    import jax

    return jax.jit(functools.partial(_hybrid_wave32_impl, tail_cap))


def build_hybrid_wave32(graph: HybridGraph, tail_cap: int = 8192):
    """(state0, wave32) for one graph; same contract as build_pull_wave32."""
    garrays = hybrid_graph_arrays(graph)
    step = hybrid_wave32_step(tail_cap)

    def wave32(seed_bits, state):
        return step(garrays, seed_bits, state)

    wave32.garrays = garrays
    wave32.step = step
    wave32.impl = functools.partial(_hybrid_wave32_impl, tail_cap)
    return hybrid_init_state(graph.n_tot), wave32
