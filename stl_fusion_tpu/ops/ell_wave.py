"""Work-efficient invalidation waves: ELL adjacency + bucketed frontiers.

The dense edge-parallel kernel (wave.py) costs O(total edges) per BFS level
— the right shape for huge frontiers, hopeless for the common case where a
wave touches 0.1-10% of a 10M-node graph. This module is the work-efficient
path: per level it reads only the out-edges of the ACTIVE frontier.

Two TPU-specific problems and their solutions:

1. **Power-law out-degree vs static shapes.** A hub node (a config value
   ten thousand views depend on) has out-degree ~10⁴; padding every node's
   edge list to the max is unusable. The graph is therefore rewritten into
   **ELL form with virtual forwarding trees**: every node keeps at most
   ``k`` out-slots; a node with more dependents fans out through a k-ary
   tree of virtual nodes (built statically, `build_ell`). This bounds the
   per-level row width at the cost of +log_k(degree) wave depth for hub
   cascades — latency for bandwidth, the right trade on a machine that
   hates gathers and loves dense rows.

2. **Frontier sizes vary wildly** (SURVEY.md §7 hard parts). Static shapes
   would force every level to pay the worst-case frontier. Instead the
   kernel compiles a ladder of frontier **buckets** (1k → … → F_max) and
   `lax.switch`es per level into the smallest bucket that fits — so a
   1k-node level costs a 1k-slot program, not a 10M-slot one.

Dedup inside a level picks its strategy per bucket at build time: small
buckets sort the fired destinations (touches only O(frontier·k) elements —
the lone-wave latency path), wide buckets use a claim-by-scatter-max trick
(first edge slot to claim a destination wins; one O(n_tot) fill costs less
than sorting a near-graph-sized frontier). No host round trips anywhere in
the wave.
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = ["EllGraph", "build_ell", "build_ell_wave"]


class EllGraph(NamedTuple):
    """Host-built ELL graph (device arrays created by the wave builder)."""

    ell_dst: np.ndarray  # int32[n_tot+1, k] — out-slot targets; pad = n_tot
    ell_epoch: np.ndarray  # int32[n_tot+1, k] — captured target epochs; pad -1
    is_real: np.ndarray  # bool[n_tot+1] — False for virtual forwarding nodes
    n_real: int
    n_tot: int
    k: int


def build_ell(
    src: np.ndarray, dst: np.ndarray, n_nodes: int, k: int = 4, use_native: bool = True
) -> EllGraph:
    """Rewrite an edge list into ELL(k) with virtual forwarding trees.

    Native counting-sort packer when available (~1 s at 10M nodes vs ~28 s
    for the numpy path below); virtual-id NUMBERING may differ between the
    two, reachability semantics are identical (tests cross-check both).

    Numpy path: layered construction, fully vectorized — in each round,
    nodes whose current out-list exceeds ``k`` get their list chunked into
    groups of ``k`` hung under fresh virtual nodes; the virtual ids become
    the node's new out-list. Rounds ≈ log_k(max_degree).
    """
    if use_native:
        from ..native import native_build_ell

        res = native_build_ell(src, dst, n_nodes, k)
        if res is not None:
            ell_dst, n_tot = res
            ell_epoch = np.where(ell_dst != n_tot, 0, -1).astype(np.int32)
            is_real = np.zeros(n_tot + 1, dtype=bool)
            is_real[:n_nodes] = True
            return EllGraph(ell_dst, ell_epoch, is_real, n_nodes, n_tot, k)

    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    next_virtual = n_nodes
    final_src: List[np.ndarray] = []
    final_dst: List[np.ndarray] = []

    cur_src, cur_dst = src, dst
    while len(cur_src):
        order = np.argsort(cur_src, kind="stable")
        s, d = cur_src[order], cur_dst[order]
        # rank of each edge within its source group
        uniq, starts, counts = np.unique(s, return_index=True, return_counts=True)
        rank = np.arange(len(s)) - np.repeat(starts, counts)
        deg = np.repeat(counts, counts)
        small = deg <= k
        final_src.append(s[small])
        final_dst.append(d[small])
        # big groups: chunk into virtual nodes of k
        bs, bd, brank = s[~small], d[~small], rank[~small]
        if len(bs) == 0:
            break
        # chunk index within the big group
        chunk = brank // k
        # assign one virtual id per (source, chunk)
        grp_key = np.stack([bs, chunk], axis=1)
        _, grp_first, grp_inv = np.unique(
            grp_key[:, 0] * (chunk.max() + 1) + grp_key[:, 1],
            return_index=True,
            return_inverse=True,
        )
        n_virtual = len(grp_first)
        virtual_ids = next_virtual + np.arange(n_virtual)
        next_virtual += n_virtual
        # edges virtual → original dst (these are ≤ k per virtual by chunking)
        final_src.append(virtual_ids[grp_inv])
        final_dst.append(bd)
        # next round: source → its virtual children (dedup (src, chunk))
        cur_src = bs[grp_first]
        cur_dst = virtual_ids

    n_tot = next_virtual
    ell_dst = np.full((n_tot + 1, k), n_tot, dtype=np.int32)
    ell_epoch = np.full((n_tot + 1, k), -1, dtype=np.int32)
    fs = np.concatenate(final_src)
    fd = np.concatenate(final_dst)
    order = np.argsort(fs, kind="stable")
    fs, fd = fs[order], fd[order]
    uniq, starts, counts = np.unique(fs, return_index=True, return_counts=True)
    slot = np.arange(len(fs)) - np.repeat(starts, counts)
    assert slot.max() < k, "ELL transform failed to bound out-degree"
    ell_dst[fs, slot] = fd
    ell_epoch[fs, slot] = 0  # all targets start at epoch 0
    is_real = np.zeros(n_tot + 1, dtype=bool)
    is_real[:n_nodes] = True
    return EllGraph(ell_dst, ell_epoch, is_real, n_nodes, n_tot, k)


class EllWaveState(NamedTuple):
    node_epoch: "object"  # int32[n_tot+1]
    invalid: "object"  # bool[n_tot+1]


class EllGraphArrays(NamedTuple):
    """Device-resident ELL adjacency, passed to the kernel as runtime args
    (never jit-closure captures — a 10M-node table embedded as an HLO
    constant makes the compile payload hundreds of MB; see pull_wave.py)."""

    ell_dst: "object"  # int32[n_tot+1, k]
    ell_epoch: "object"  # int32[n_tot+1, k]
    is_real: "object"  # bool[n_tot+1]


def build_ell_wave(
    graph: EllGraph,
    f_max: Optional[int] = None,
    buckets: Optional[Sequence[int]] = None,
):
    """Compile the bucketed work-efficient wave for an ELL graph.

    Returns (initial_state, wave_fn) where
    ``wave_fn(seed_ids_padded, state) -> (state, real_invalidated_count)``;
    ``seed_ids_padded`` is int32[seed_cap] padded with -1. The whole wave —
    all levels, bucket switching, dedup — runs in one XLA program. The
    device adjacency is exposed as ``wave_fn.garrays`` / raw jitted kernel
    as ``wave_fn.step`` for callers composing a larger jitted program.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_tot, k = graph.n_tot, graph.k
    if f_max is None:
        # must bound the widest possible level (worst case: the whole graph)
        f_max = 1 << int(np.ceil(np.log2(max(n_tot, 1 << 14))))
    if buckets is None:
        buckets = []
        b = 1 << 10  # small head buckets keep shallow lone waves on the
        while b < f_max:  # sort-dedup path (µs-scale levels)
            buckets.append(b)
            b <<= 3
        buckets.append(f_max)
    buckets = [min(b, f_max) for b in buckets]

    garrays = EllGraphArrays(
        ell_dst=jnp.asarray(graph.ell_dst),
        ell_epoch=jnp.asarray(graph.ell_epoch),
        is_real=jnp.asarray(graph.is_real),
    )

    def init_state() -> EllWaveState:
        node_epoch = jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2)
        invalid = jnp.zeros(n_tot + 1, dtype=jnp.bool_)
        return EllWaveState(node_epoch, invalid)

    def _sort_dedup(mask, ids):
        """(winners, isnew): sort ``ids`` (masked-out → null), keep the
        first of each run of equal ids. Touches only O(len(ids)) elements —
        the small-bucket / seed-stage dedup."""
        skeys = jnp.sort(jnp.where(mask, ids, n_tot).astype(jnp.int32))
        isnew = (skeys < n_tot) & jnp.concatenate(
            [jnp.ones(1, dtype=bool), skeys[1:] != skeys[:-1]]
        )
        return skeys, isnew

    def _level(bsize: int, F, invalid, node_epoch, ell_dst, ell_epoch, is_real):
        """Expand F[:bsize] one level; returns (F_next, nF_next, invalid, newly_real).

        Dedup strategy is picked per bucket at build time:
        - small buckets SORT the fired dsts (O(m log² m), m = bsize*k) — no
          full-graph array is touched, so a shallow lone wave costs µs, not
          an O(n_tot) zero-fill per level;
        - wide buckets use the claim scatter (O(n_tot)) where the sort
          would cost more than the fill.
        F is updated IN PLACE: stale entries beyond nF_next are ids from
        earlier frontiers, whose eligible dsts are already invalid, so
        re-expanding them can never re-fire (fire tests ~invalid[dst]).
        """
        Fb = lax.slice(F, (0,), (bsize,))
        rows = ell_dst[Fb]  # (bsize, k) row gather; pad rows → n_tot
        eps = ell_epoch[Fb]
        cur = node_epoch[rows]
        inv = invalid[rows]
        fire = (cur == eps) & ~inv & (rows < n_tot)
        flat_dst = rows.reshape(-1)
        flat_fire = fire.reshape(-1)
        invalid = invalid.at[flat_dst].max(flat_fire)
        m = bsize * k
        if m * max(int(np.log2(m)), 1) < n_tot:
            winners, isnew = _sort_dedup(flat_fire, flat_dst)
        else:
            # claim dedup: first firing slot per destination wins
            slot_id = jnp.arange(m, dtype=jnp.int32) + 1
            claim = (
                jnp.zeros(n_tot + 1, dtype=jnp.int32)
                .at[flat_dst]
                .max(jnp.where(flat_fire, slot_id, 0))
            )
            isnew = flat_fire & (claim[flat_dst] == slot_id)
            winners = flat_dst.astype(jnp.int32)
        pos = jnp.cumsum(isnew.astype(jnp.int32)) - 1
        nF_next = isnew.sum(dtype=jnp.int32)
        scatter_pos = jnp.where(isnew, pos, f_max + 1)  # OOB → dropped
        F_next = F.at[scatter_pos].set(winners, mode="drop")
        newly_real = (isnew & is_real[winners]).sum(dtype=jnp.int32)
        return F_next, nF_next, invalid, newly_real

    branches = [
        functools.partial(_level, b) for b in buckets
    ]

    def level_switch(F, nF, invalid, node_epoch, ell_dst, ell_epoch, is_real):
        # smallest bucket that fits nF
        bidx = jnp.searchsorted(jnp.asarray(buckets, dtype=jnp.int32), nF, side="left")
        bidx = jnp.minimum(bidx, len(buckets) - 1)
        return lax.switch(bidx, branches, F, invalid, node_epoch, ell_dst, ell_epoch, is_real)

    @jax.jit
    def step(g: EllGraphArrays, seed_ids: "jax.Array", state: EllWaveState):
        ell_dst, ell_epoch, is_real = g
        node_epoch, invalid = state.node_epoch, state.invalid
        # seed frontier: pad -1 → n_tot slot; only fresh (not-invalid)
        # seeds, deduped by sorting the (small) seed vector — a claim
        # scatter here would cost an O(n_tot) zero-fill per wave, the
        # dominant term of a shallow lone wave's latency at 10M nodes
        safe = jnp.where(seed_ids >= 0, seed_ids, n_tot).astype(jnp.int32)
        candidate = (safe < n_tot) & ~invalid[safe]
        skeys, fresh = _sort_dedup(candidate, safe)
        invalid = invalid.at[skeys].max(fresh)
        count0 = (fresh & is_real[skeys]).sum(dtype=jnp.int32)
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        F0 = (
            jnp.full(f_max, n_tot, dtype=jnp.int32)
            .at[jnp.where(fresh, pos, f_max + 1)]
            .set(skeys, mode="drop")
        )
        nF0 = fresh.sum(dtype=jnp.int32)

        def cond(carry):
            _F, nF, _inv, _cnt = carry
            return nF > 0

        def body(carry):
            F, nF, invalid, cnt = carry
            F2, nF2, invalid, newly = level_switch(
                F, nF, invalid, node_epoch, ell_dst, ell_epoch, is_real
            )
            return F2, nF2, invalid, cnt + newly

        _F, _nF, invalid, count = lax.while_loop(cond, body, (F0, nF0, invalid, count0))
        return EllWaveState(node_epoch, invalid), count

    def wave(seed_ids, state):
        return step(garrays, seed_ids, state)

    wave.garrays = garrays
    wave.step = step
    return init_state(), wave
