"""Work-efficient invalidation waves: ELL adjacency + bucketed frontiers.

The dense edge-parallel kernel (wave.py) costs O(total edges) per BFS level
— the right shape for huge frontiers, hopeless for the common case where a
wave touches 0.1-10% of a 10M-node graph. This module is the work-efficient
path: per level it reads only the out-edges of the ACTIVE frontier.

Two TPU-specific problems and their solutions:

1. **Power-law out-degree vs static shapes.** A hub node (a config value
   ten thousand views depend on) has out-degree ~10⁴; padding every node's
   edge list to the max is unusable. The graph is therefore rewritten into
   **ELL form with virtual forwarding trees**: every node keeps at most
   ``k`` out-slots; a node with more dependents fans out through a k-ary
   tree of virtual nodes (built statically, `build_ell`). This bounds the
   per-level row width at the cost of +log_k(degree) wave depth for hub
   cascades — latency for bandwidth, the right trade on a machine that
   hates gathers and loves dense rows.

2. **Frontier sizes vary wildly** (SURVEY.md §7 hard parts). Static shapes
   would force every level to pay the worst-case frontier. Instead the
   kernel compiles a ladder of frontier **buckets** (1k → … → F_max) and
   `lax.switch`es per level into the smallest bucket that fits — so a
   1k-node level costs a 1k-slot program, not a 10M-slot one.

Dedup inside a level picks its strategy per bucket at build time: small
buckets sort the fired destinations (touches only O(frontier·k) elements —
the lone-wave latency path), wide buckets use a claim-by-scatter-max trick
(first edge slot to claim a destination wins; one O(n_tot) fill costs less
than sorting a near-graph-sized frontier). No host round trips anywhere in
the wave.
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "EllGraph",
    "EllWaveState",
    "advance_epoch",
    "build_ell",
    "build_ell_lat_wave",
    "build_ell_wave",
    "ell_live_epoch_init",
    "ell_live_union_chain_step",
    "ell_live_union_step",
    "invalid_mask",
    "widen_ell",
]


class EllGraph(NamedTuple):
    """Host-built ELL graph (device arrays created by the wave builder)."""

    ell_dst: np.ndarray  # int32[n_tot+1, k] — out-slot targets; pad = n_tot
    ell_epoch: np.ndarray  # int32[n_tot+1, k] — captured target epochs; pad -1
    is_real: np.ndarray  # bool[n_tot+1] — False for virtual forwarding nodes
    n_real: int
    n_tot: int
    k: int


def build_ell(
    src: np.ndarray, dst: np.ndarray, n_nodes: int, k: int = 4, use_native: bool = True
) -> EllGraph:
    """Rewrite an edge list into ELL(k) with virtual forwarding trees.

    Native counting-sort packer when available (~1 s at 10M nodes vs ~28 s
    for the numpy path below); virtual-id NUMBERING may differ between the
    two, reachability semantics are identical (tests cross-check both).

    Numpy path: layered construction, fully vectorized — in each round,
    nodes whose current out-list exceeds ``k`` get their list chunked into
    groups of ``k`` hung under fresh virtual nodes; the virtual ids become
    the node's new out-list. Rounds ≈ log_k(max_degree).
    """
    if use_native:
        from ..native import native_build_ell

        res = native_build_ell(src, dst, n_nodes, k)
        if res is not None:
            ell_dst, n_tot = res
            ell_epoch = np.where(ell_dst != n_tot, 0, -1).astype(np.int32)
            is_real = np.zeros(n_tot + 1, dtype=bool)
            is_real[:n_nodes] = True
            return EllGraph(ell_dst, ell_epoch, is_real, n_nodes, n_tot, k)

    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    next_virtual = n_nodes
    final_src: List[np.ndarray] = []
    final_dst: List[np.ndarray] = []

    cur_src, cur_dst = src, dst
    while len(cur_src):
        order = np.argsort(cur_src, kind="stable")
        s, d = cur_src[order], cur_dst[order]
        # rank of each edge within its source group
        uniq, starts, counts = np.unique(s, return_index=True, return_counts=True)
        rank = np.arange(len(s)) - np.repeat(starts, counts)
        deg = np.repeat(counts, counts)
        small = deg <= k
        final_src.append(s[small])
        final_dst.append(d[small])
        # big groups: chunk into virtual nodes of k
        bs, bd, brank = s[~small], d[~small], rank[~small]
        if len(bs) == 0:
            break
        # chunk index within the big group
        chunk = brank // k
        # assign one virtual id per (source, chunk)
        grp_key = np.stack([bs, chunk], axis=1)
        _, grp_first, grp_inv = np.unique(
            grp_key[:, 0] * (chunk.max() + 1) + grp_key[:, 1],
            return_index=True,
            return_inverse=True,
        )
        n_virtual = len(grp_first)
        virtual_ids = next_virtual + np.arange(n_virtual)
        next_virtual += n_virtual
        # edges virtual → original dst (these are ≤ k per virtual by chunking)
        final_src.append(virtual_ids[grp_inv])
        final_dst.append(bd)
        # next round: source → its virtual children (dedup (src, chunk))
        cur_src = bs[grp_first]
        cur_dst = virtual_ids

    n_tot = next_virtual
    ell_dst = np.full((n_tot + 1, k), n_tot, dtype=np.int32)
    ell_epoch = np.full((n_tot + 1, k), -1, dtype=np.int32)
    fs = np.concatenate(final_src) if final_src else np.empty(0, np.int64)
    fd = np.concatenate(final_dst) if final_dst else np.empty(0, np.int64)
    if len(fs):
        order = np.argsort(fs, kind="stable")
        fs, fd = fs[order], fd[order]
        uniq, starts, counts = np.unique(fs, return_index=True, return_counts=True)
        slot = np.arange(len(fs)) - np.repeat(starts, counts)
        assert slot.max() < k, "ELL transform failed to bound out-degree"
        ell_dst[fs, slot] = fd
        ell_epoch[fs, slot] = 0  # all targets start at epoch 0
    is_real = np.zeros(n_tot + 1, dtype=bool)
    is_real[:n_nodes] = True
    return EllGraph(ell_dst, ell_epoch, is_real, n_nodes, n_tot, k)


def widen_ell(graph: EllGraph, extra: int) -> EllGraph:
    """Append ``extra`` guaranteed-free pad columns to every row — slot
    headroom for in-place patching (a packed row would otherwise break the
    live mirror's patch log on the first new edge landing on it)."""
    if extra <= 0:
        return graph
    rows = graph.ell_dst.shape[0]
    return graph._replace(
        ell_dst=np.hstack(
            [graph.ell_dst, np.full((rows, extra), graph.n_tot, dtype=np.int32)]
        ),
        ell_epoch=np.hstack(
            [graph.ell_epoch, np.full((rows, extra), -1, dtype=np.int32)]
        ),
        k=graph.k + extra,
    )


class EllWaveState(NamedTuple):
    """Persistent wave state. ``invalid`` is epoch-stamped rather than a
    bool mask: node x is invalid iff ``inv_stamp[x] == epoch``. Marking the
    whole graph consistent again (the churn model between waves, or a bulk
    recompute) is then ``epoch + 1`` — O(1) instead of an O(n) device fill,
    which WAS the 10M lone-wave latency floor (PERF.md r1). ``frontier`` is
    the persistent scratch frontier buffer: levels only ever read slots
    below the live count (masked in-kernel), so it is never cleared — the
    other O(f_max) per-wave fill the r1 kernel paid."""

    node_epoch: "object"  # int32[n_tot+1]
    inv_stamp: "object"  # int32[n_tot+1] — last epoch each node was invalidated in
    epoch: "object"  # int32 scalar — current consistency epoch (≥ 1)
    frontier: "object"  # int32[f_max] scratch; slots ≥ live count are stale


def advance_epoch(state: EllWaveState) -> EllWaveState:
    """All nodes consistent again (bulk 'recompute') in O(1): stale stamps
    from earlier epochs can never equal the new epoch."""
    return state._replace(epoch=state.epoch + 1)


def invalid_mask(state: EllWaveState) -> np.ndarray:
    """bool[n_tot+1] — the materialized invalid set (readback helper)."""
    return np.asarray(state.inv_stamp) == int(state.epoch)


class EllGraphArrays(NamedTuple):
    """Device-resident ELL adjacency, passed to the kernel as runtime args
    (never jit-closure captures — a 10M-node table embedded as an HLO
    constant makes the compile payload hundreds of MB; see pull_wave.py)."""

    ell_dst: "object"  # int32[n_tot+1, k]
    ell_epoch: "object"  # int32[n_tot+1, k]
    is_real: "object"  # bool[n_tot+1]


def build_ell_wave(
    graph: EllGraph,
    f_max: Optional[int] = None,
    buckets: Optional[Sequence[int]] = None,
):
    """Compile the bucketed work-efficient wave for an ELL graph.

    Returns (initial_state, wave_fn) where
    ``wave_fn(seed_ids_padded, state) -> (state, real_invalidated_count)``;
    ``seed_ids_padded`` is int32[seed_cap] padded with -1. The whole wave —
    all levels, bucket switching, dedup — runs in one XLA program. The
    device adjacency is exposed as ``wave_fn.garrays`` / raw jitted kernel
    as ``wave_fn.step`` for callers composing a larger jitted program.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_tot, k = graph.n_tot, graph.k
    if f_max is None:
        # must bound the widest possible level (worst case: the whole graph)
        f_max = 1 << int(np.ceil(np.log2(max(n_tot, 1 << 14))))
    if buckets is None:
        buckets = []
        b = 1 << 10  # small head buckets keep shallow lone waves on the
        while b < f_max:  # sort-dedup path (µs-scale levels)
            buckets.append(b)
            b <<= 3
        buckets.append(f_max)
    buckets = [min(b, f_max) for b in buckets]

    garrays = EllGraphArrays(
        ell_dst=jnp.asarray(graph.ell_dst),
        ell_epoch=jnp.asarray(graph.ell_epoch),
        is_real=jnp.asarray(graph.is_real),
    )

    def init_state() -> EllWaveState:
        node_epoch = jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2)
        inv_stamp = jnp.zeros(n_tot + 1, dtype=jnp.int32)
        # epoch starts at 1 so the zero-initialized stamps mean "consistent"
        return EllWaveState(
            node_epoch,
            inv_stamp,
            jnp.asarray(1, dtype=jnp.int32),
            jnp.full(f_max, n_tot, dtype=jnp.int32),  # the ONLY f_max fill, ever
        )

    def _sort_dedup(mask, ids):
        """(winners, isnew): sort ``ids`` (masked-out → null), keep the
        first of each run of equal ids. Touches only O(len(ids)) elements —
        the small-bucket / seed-stage dedup."""
        skeys = jnp.sort(jnp.where(mask, ids, n_tot).astype(jnp.int32))
        isnew = (skeys < n_tot) & jnp.concatenate(
            [jnp.ones(1, dtype=bool), skeys[1:] != skeys[:-1]]
        )
        return skeys, isnew

    NEVER = jnp.asarray(np.int32(-(2**31)), dtype=jnp.int32)  # stamp scatter filler

    def _level(bsize: int, F, nF, inv_stamp, epoch, node_epoch, ell_dst, ell_epoch, is_real):
        """Expand F[:bsize] one level; returns (F_next, nF_next, inv_stamp, newly_real).

        Dedup strategy is picked per bucket at build time:
        - small buckets SORT the fired dsts (O(m log² m), m = bsize*k) — no
          full-graph array is touched, so a shallow lone wave costs µs, not
          an O(n_tot) zero-fill per level;
        - wide buckets use the claim scatter (O(n_tot)) where the sort
          would cost more than the fill.
        F persists across levels AND waves: slots ≥ nF hold stale ids from
        earlier frontiers, and the slot mask below keeps them from firing —
        so F never needs an O(f_max) re-fill, whatever happens to the
        invalid set between waves (epoch bumps included).
        """
        Fb = lax.slice(F, (0,), (bsize,))
        slot_live = jnp.arange(bsize, dtype=jnp.int32) < nF
        rows = ell_dst[Fb]  # (bsize, k) row gather; pad rows → n_tot
        eps = ell_epoch[Fb]
        cur = node_epoch[rows]
        inv = inv_stamp[rows] == epoch
        fire = slot_live[:, None] & (cur == eps) & ~inv & (rows < n_tot)
        flat_dst = rows.reshape(-1)
        flat_fire = fire.reshape(-1)
        inv_stamp = inv_stamp.at[flat_dst].max(jnp.where(flat_fire, epoch, NEVER))
        m = bsize * k
        if m * max(int(np.log2(m)), 1) < n_tot:
            winners, isnew = _sort_dedup(flat_fire, flat_dst)
        else:
            # claim dedup: first firing slot per destination wins
            slot_id = jnp.arange(m, dtype=jnp.int32) + 1
            claim = (
                jnp.zeros(n_tot + 1, dtype=jnp.int32)
                .at[flat_dst]
                .max(jnp.where(flat_fire, slot_id, 0))
            )
            isnew = flat_fire & (claim[flat_dst] == slot_id)
            winners = flat_dst.astype(jnp.int32)
        pos = jnp.cumsum(isnew.astype(jnp.int32)) - 1
        nF_next = isnew.sum(dtype=jnp.int32)
        scatter_pos = jnp.where(isnew, pos, f_max + 1)  # OOB → dropped
        F_next = F.at[scatter_pos].set(winners, mode="drop")
        newly_real = (isnew & is_real[winners]).sum(dtype=jnp.int32)
        return F_next, nF_next, inv_stamp, newly_real

    branches = [
        functools.partial(_level, b) for b in buckets
    ]

    def level_switch(F, nF, inv_stamp, epoch, node_epoch, ell_dst, ell_epoch, is_real):
        # smallest bucket that fits nF
        bidx = jnp.searchsorted(jnp.asarray(buckets, dtype=jnp.int32), nF, side="left")
        bidx = jnp.minimum(bidx, len(buckets) - 1)
        return lax.switch(
            bidx, branches, F, nF, inv_stamp, epoch, node_epoch, ell_dst, ell_epoch, is_real
        )

    @jax.jit
    def step(g: EllGraphArrays, seed_ids: "jax.Array", state: EllWaveState):
        ell_dst, ell_epoch, is_real = g
        node_epoch, inv_stamp, epoch, F = state
        # seed frontier: pad -1 → n_tot slot; only fresh (not-invalid)
        # seeds, deduped by sorting the (small) seed vector — a claim
        # scatter here would cost an O(n_tot) zero-fill per wave, the
        # dominant term of a shallow lone wave's latency at 10M nodes
        safe = jnp.where(seed_ids >= 0, seed_ids, n_tot).astype(jnp.int32)
        candidate = (safe < n_tot) & (inv_stamp[safe] != epoch)
        skeys, fresh = _sort_dedup(candidate, safe)
        inv_stamp = inv_stamp.at[skeys].max(jnp.where(fresh, epoch, NEVER))
        count0 = (fresh & is_real[skeys]).sum(dtype=jnp.int32)
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        F0 = F.at[jnp.where(fresh, pos, f_max + 1)].set(skeys, mode="drop")
        nF0 = fresh.sum(dtype=jnp.int32)

        def cond(carry):
            _F, nF, _inv, _cnt = carry
            return nF > 0

        def body(carry):
            F, nF, inv_stamp, cnt = carry
            F2, nF2, inv_stamp, newly = level_switch(
                F, nF, inv_stamp, epoch, node_epoch, ell_dst, ell_epoch, is_real
            )
            return F2, nF2, inv_stamp, cnt + newly

        F, _nF, inv_stamp, count = lax.while_loop(cond, body, (F0, nF0, inv_stamp, count0))
        return EllWaveState(node_epoch, inv_stamp, epoch, F), count

    def wave(seed_ids, state):
        return step(garrays, seed_ids, state)

    wave.garrays = garrays
    wave.step = step
    return init_state(), wave


def build_ell_lat_wave(
    graph: EllGraph,
    lcap: int = 1024,
    cap: int = 16384,
    assume_static_epochs: bool = False,
):
    """The LONE-WAVE latency kernel: a shallow edit's cascade in O(wave)
    device work with NO scatters inside the level loop.

    Measured on v5e (op_probe, r2): a scatter of even 256 lanes into a
    16M-element array costs ~31 µs and grows with lane count (~276 µs at
    4096), while sorts of ≤64K elements cost 12-55 µs and small gathers
    ~21 µs — so the general kernel's per-level scatter pair (stamp mark +
    frontier compaction) IS the 10M lone-wave latency floor (~1.2 ms per
    level). This kernel therefore:

    - keeps the level frontier COMPACT (int32[lcap] ids, not a mask);
    - dedups and tests membership by TAGGED MERGE-SORT against the sorted
      accumulated-wave id list (int32[cap]) — a sort replaces both the
      stamp scatter and the claim scatter;
    - compacts the next frontier by sorting candidate ids (new ids first,
      pads last) and slicing — a sort replaces the position scatter;
    - commits ``inv_stamp`` ONCE at wave end (a single scatter).

    Capacity overflow (wave wider than ``lcap`` per level or ``cap`` total)
    aborts WITHOUT touching state and reports ``overflow=True``; the caller
    re-runs the wave on the general bucketed kernel. Shares ``EllWaveState``
    with ``build_ell_wave`` (the persistent ``frontier`` scratch is unused
    here).

    ``assume_static_epochs=True`` additionally elides the per-level epoch
    gathers — valid ONLY for graphs whose topology never mutates after
    build (all captured edge epochs stay equal to their node epochs, e.g.
    the synthetic bench graphs); the builder verifies the precondition.

    Returns (initial_state, lat_wave) with
    ``lat_wave(seed_ids, state) -> (state, count, overflow)``; the raw
    jitted kernel is ``lat_wave.step``, device adjacency ``lat_wave.garrays``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_tot, k = graph.n_tot, graph.k
    if 2 * (n_tot + 1) >= 2**31:
        raise ValueError("tagged-sort keys need 2*(n_tot+1) < 2^31")
    if assume_static_epochs:
        live_slots = graph.ell_dst != n_tot
        if not (graph.ell_epoch[live_slots] == 0).all():
            raise ValueError(
                "assume_static_epochs requires all captured edge epochs == 0"
            )

    garrays = EllGraphArrays(
        ell_dst=jnp.asarray(graph.ell_dst),
        ell_epoch=jnp.asarray(graph.ell_epoch),
        is_real=jnp.asarray(graph.is_real),
    )
    NEVER = jnp.asarray(np.int32(-(2**31)), dtype=jnp.int32)

    def init_state() -> EllWaveState:
        node_epoch = jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2)
        return EllWaveState(
            node_epoch,
            jnp.zeros(n_tot + 1, dtype=jnp.int32),
            jnp.asarray(1, dtype=jnp.int32),
            jnp.zeros(0, dtype=jnp.int32),  # frontier scratch unused
        )

    def _dedup_first(sorted_ids):
        prev = jnp.concatenate([jnp.full(1, -1, jnp.int32), sorted_ids[:-1]])
        return sorted_ids != prev

    @jax.jit
    def step(g: EllGraphArrays, seed_ids: "jax.Array", state: EllWaveState):
        ell_dst, ell_epoch, is_real = g
        node_epoch, inv_stamp, epoch, scratch = state

        # ---- seed stage: dedup by sort, no graph-sized work
        safe = jnp.where(seed_ids >= 0, seed_ids, n_tot).astype(jnp.int32)
        ok = (safe < n_tot) & (inv_stamp[safe] != epoch)
        skeys = jnp.sort(jnp.where(ok, safe, n_tot))
        fresh = _dedup_first(skeys) & (skeys < n_tot)
        nF0 = fresh.sum(dtype=jnp.int32)
        F0 = lax.dynamic_slice_in_dim(
            jnp.sort(jnp.where(fresh, skeys, n_tot)), 0, min(lcap, skeys.shape[0])
        )
        if F0.shape[0] < lcap:
            F0 = jnp.concatenate([F0, jnp.full(lcap - F0.shape[0], n_tot, jnp.int32)])
        acc0 = jnp.full(cap, n_tot, dtype=jnp.int32).at[: skeys.shape[0]].set(
            jnp.where(fresh, skeys, n_tot)
        )
        acc0 = jnp.sort(acc0)
        over0 = nF0 > lcap

        def cond(carry):
            _F, nF, _acc, _nacc, over = carry
            return (nF > 0) & ~over

        def body(carry):
            F, nF, acc, n_acc, over = carry
            slot_live = jnp.arange(lcap, dtype=jnp.int32) < nF
            rows = ell_dst[F]  # [lcap, k]
            stamp = inv_stamp[rows]
            live = (stamp != epoch) & (rows < n_tot)
            if not assume_static_epochs:
                # live-graph version matching; on an immutable-topology
                # graph every slot's captured epoch equals the node epoch,
                # so both gathers are elided (two fewer gathers per level —
                # the gathers are the level cost floor, see op_probe r2)
                eps = ell_epoch[F]
                cur = node_epoch[rows]
                live = live & (cur == eps)
            cand_ok = slot_live[:, None] & live
            cand = jnp.where(cand_ok, rows, n_tot).reshape(-1)
            # tagged merge: acc entries (even) sort before candidates (odd)
            keys = jnp.sort(jnp.concatenate([acc * 2, cand * 2 + 1]))
            ids = keys >> 1
            first = _dedup_first(ids) & (ids < n_tot)
            isnew = first & ((keys & 1) == 1)
            nF_next = isnew.sum(dtype=jnp.int32)
            F_next = jnp.sort(jnp.where(isnew, ids, n_tot))[:lcap]
            n_all = first.sum(dtype=jnp.int32)
            acc_next = jnp.sort(jnp.where(first, ids, n_tot))[:cap]
            over = over | (nF_next > lcap) | (n_all > cap)
            return F_next, nF_next, acc_next, n_all, over

        _F, _nF, acc, _nacc, over = lax.while_loop(
            cond, body, (F0, nF0, acc0, nF0, over0)
        )

        # ---- single commit: stamp the whole wave at once (masked on overflow)
        valid = (acc < n_tot) & ~over
        inv_stamp = inv_stamp.at[jnp.where(valid, acc, n_tot)].max(
            jnp.where(valid, epoch, NEVER), mode="drop"
        )
        count = jnp.where(over, 0, (valid & is_real[acc]).sum(dtype=jnp.int32))
        return EllWaveState(node_epoch, inv_stamp, epoch, scratch), count, over

    def lat_wave(seed_ids, state):
        return step(garrays, seed_ids, state)

    lat_wave.garrays = garrays
    lat_wave.step = step
    return init_state(), lat_wave


@functools.lru_cache(maxsize=8)
def ell_live_epoch_init(n_nodes: int, n_cap: int):
    """Jitted derivation of the lat mirror's per-slot captured epochs from
    the ALREADY-RESIDENT dense epoch array — the mirror's second big table
    costs one device op instead of a second multi-hundred-MB upload through
    the relay. Slot dst real → its current epoch; virtual/pad → 0 (virtual
    forwarding nodes never version)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def derive(ell_dst, node_epoch):
        real = ell_dst < n_nodes
        return jnp.where(real, node_epoch[jnp.clip(ell_dst, 0, n_cap)], 0)

    return derive


@functools.lru_cache(maxsize=8)
def ell_live_union_step(
    n_tot: int, n_nodes: int, n_cap: int, lcap: int, cap: int
):
    """The LIVE lone-wave kernel (VERDICT r4 #1): O(closure) union expansion
    over the lat mirror's out-ELL, gated by the LIVE dense state, in ONE
    dispatch — the bridge that routes ``cascade_rows_batch``'s small seed
    sets through the scatter-free small-wave machinery instead of a full
    topo-table sweep (718 ms p99 at 10 M in BENCH_r04; the reference's
    invalidation cost is ∝ dependents, Computed.cs:162-230).

    Mechanics = :func:`build_ell_lat_wave` (compact sorted frontier, tagged
    merge-sort dedup against the accumulated wave, one commit) with the
    static kernel's epoch-stamp state replaced by the live graph's own
    arrays, both resident:

    - liveness: slot (u→d) fires iff d is virtual (forwarding trees never
      version) or ``node_epoch[d] == ell_epoch[u,slot]`` — the captured-at-
      epoch rule, so a bumped dependent's old in-edges are dead without any
      mirror maintenance, and a patched re-capture carries its new epoch;
    - blocking: an already-invalid REAL node neither counts, re-fires, nor
      conducts (the dense-BFS union rule); seeds conduct even when already
      invalid but never count;
    - commit: newly-invalid real ids scatter straight into the dense
      ``g_invalid`` array (device-resident result state — the same array
      every other wave path updates) and come back compacted (≤ ``cap``).

    Frontier > ``lcap`` per level or wave > ``cap`` total aborts WITHOUT
    touching state (``overflow=True``); the caller re-runs on the topo
    sweep. Returns jitted ``step(ell_dst, ell_epoch, node_epoch, g_invalid,
    seed_ids) -> (g_invalid2, count, acc_ids, overflow)``; ``acc_ids`` is
    the sorted wave id list (real + virtual, pads ``n_tot``) — the host
    filters ``< n_nodes``."""
    import jax

    return jax.jit(_live_union_core(n_tot, n_nodes, n_cap, lcap, cap))


def _live_union_core(n_tot: int, n_nodes: int, n_cap: int, lcap: int, cap: int):
    """Traceable single-wave core shared by the lone-wave step and the
    chained variant: ``core(ell_dst, ell_epoch, node_epoch, g_invalid,
    seed_ids) -> (g_invalid2, count, acc, over)``."""
    import jax.numpy as jnp
    from jax import lax

    if 2 * (n_tot + 1) >= 2**31:
        raise ValueError("tagged-sort keys need 2*(n_tot+1) < 2^31")

    def _dedup_first(sorted_ids):
        prev = jnp.concatenate([jnp.full(1, -1, jnp.int32), sorted_ids[:-1]])
        return sorted_ids != prev

    def core(ell_dst, ell_epoch, node_epoch, g_invalid, seed_ids):
        oob = g_invalid.shape[0]

        # ---- seed stage: dedup by sort; pre-invalid seeds CONDUCT (enter
        # the frontier) but are never newly (never enter acc)
        safe = jnp.where(
            (seed_ids >= 0) & (seed_ids < n_tot), seed_ids, n_tot
        ).astype(jnp.int32)
        skeys = jnp.sort(safe)
        uniq = _dedup_first(skeys) & (skeys < n_tot)
        pre_inv = g_invalid[jnp.clip(skeys, 0, n_cap)]
        fresh = uniq & ~pre_inv
        nF0 = uniq.sum(dtype=jnp.int32)
        F0 = jnp.sort(jnp.where(uniq, skeys, n_tot))[: min(lcap, skeys.shape[0])]
        if F0.shape[0] < lcap:
            F0 = jnp.concatenate([F0, jnp.full(lcap - F0.shape[0], n_tot, jnp.int32)])
        m0 = min(cap, skeys.shape[0])
        acc0 = jnp.full(cap, n_tot, dtype=jnp.int32).at[:m0].set(
            jnp.sort(jnp.where(fresh, skeys, n_tot))[:m0]
        )
        over0 = (nF0 > lcap) | (fresh.sum(dtype=jnp.int32) > cap)

        def cond(carry):
            _F, nF, _acc, over = carry
            return (nF > 0) & ~over

        def body(carry):
            F, nF, acc, over = carry
            rows = ell_dst[F]  # [lcap, k]; pad F entries read the null row
            eps = ell_epoch[F]
            d = rows.reshape(-1)
            e = eps.reshape(-1)
            is_pad = d >= n_tot
            is_virtual = (d >= n_nodes) & ~is_pad
            dc = jnp.clip(d, 0, n_cap)
            epoch_ok = is_virtual | (node_epoch[dc] == e)
            unblocked = is_virtual | ~g_invalid[dc]
            cand = jnp.where(~is_pad & epoch_ok & unblocked, d, n_tot)
            # tagged merge: acc entries (even) sort before candidates (odd)
            keys = jnp.sort(jnp.concatenate([acc * 2, cand * 2 + 1]))
            ids = keys >> 1
            first = _dedup_first(ids) & (ids < n_tot)
            isnew = first & ((keys & 1) == 1)
            nF_next = isnew.sum(dtype=jnp.int32)
            F_next = jnp.sort(jnp.where(isnew, ids, n_tot))[:lcap]
            n_all = first.sum(dtype=jnp.int32)
            acc_next = jnp.sort(jnp.where(first, ids, n_tot))[:cap]
            over = over | (nF_next > lcap) | (n_all > cap)
            return F_next, nF_next, acc_next, over

        _F, _nF, acc, over = lax.while_loop(cond, body, (F0, nF0, acc0, over0))

        # ---- single commit into the LIVE dense invalid array (masked out
        # entirely on overflow — state untouched, caller re-runs elsewhere)
        newly = (acc < n_nodes) & ~over
        count = newly.sum(dtype=jnp.int32)
        g_invalid2 = g_invalid.at[jnp.where(newly, acc, oob)].set(True, mode="drop")
        acc_out = jnp.where(over, jnp.full_like(acc, n_tot), acc)
        return g_invalid2, count, acc_out, over

    return core


@functools.lru_cache(maxsize=8)
def ell_live_union_chain_step(
    n_tot: int, n_nodes: int, n_cap: int, lcap: int, cap: int, out_cap: int
):
    """M INDEPENDENT lone waves SEQUENCED in one program against the live
    state: wave ``i`` sees waves ``< i``'s commits (identical final state
    and per-wave counts to M separate :func:`ell_live_union_step` calls) —
    the burst-of-single-row-invalidations API, and the shape that lets the
    live bench measure per-wave latency by CHAIN DIFFERENCE (the relay's
    per-dispatch cost cancels exactly, as in the static kernel's
    methodology). A wave that overflows commits nothing and flags its slot
    (the caller re-runs it on the topo sweep); the union readback compacts
    the combined newly set to ``out_cap``.

    Returns jitted ``step(ell_dst, ell_epoch, node_epoch, g_invalid,
    seed_mat[M, S]) -> (g_invalid2, counts[M], overs[M], out_ids[out_cap],
    out_count, out_over)``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    core = _live_union_core(n_tot, n_nodes, n_cap, lcap, cap)

    @jax.jit
    def step(ell_dst, ell_epoch, node_epoch, g_invalid, seed_mat):
        g_invalid0 = g_invalid

        def body(g_inv, seeds):
            g_inv2, count, _acc, over = core(
                ell_dst, ell_epoch, node_epoch, g_inv, seeds
            )
            return g_inv2, (count, over)

        g_invalid2, (counts, overs) = lax.scan(body, g_invalid, seed_mat)
        newly = g_invalid2 & ~g_invalid0
        out_count = newly.sum(dtype=jnp.int32)
        pos = jnp.cumsum(newly.astype(jnp.int32)) - 1
        scatter_pos = jnp.where(newly & (pos < out_cap), pos, out_cap)
        out_ids = (
            jnp.full(out_cap, -1, dtype=jnp.int32)
            .at[scatter_pos]
            .set(jnp.arange(newly.shape[0], dtype=jnp.int32), mode="drop")
        )
        return g_invalid2, counts, overs, out_ids, out_count, out_count > out_cap

    return step
