"""MemoTableBridge — scalar-graph dependencies on MemoTable rows.

Connects the columnar read path (ops/memo_table.py) to the host `Computed`
graph so both memoization worlds cascade together: a `@compute_method` that
aggregates over table rows declares its dependency via this bridge, and
`table.invalidate(ids)` then invalidates exactly the scalar nodes that used
those rows — which in turn fan out through the object graph / device wave
like any other invalidation.

Granularity is the caller's choice (the same trade every columnar system
makes):

- ``use_table()`` — one coarse leaf for the whole table; any row
  invalidation cascades. Right for whole-table aggregates.
- ``use_rows(ids)`` — per-row leaf states, created lazily; only those rows'
  invalidations cascade. Right for reads of a few hot keys. Rows that never
  had a scalar dependent cost nothing (the invalidation handler only
  touches leaves that exist).

Leaves are `MutableState` nodes carrying the table/row version — the same
settable-source machinery the reference uses for graph inputs
(State/MutableState.cs:14-175), so no new node mechanics are introduced.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np

from ..core.hub import FusionHub, default_hub
from ..state.mutable import MutableState
from .memo_table import MemoTable

__all__ = ["MemoTableBridge"]


class MemoTableBridge:
    def __init__(self, table: MemoTable, hub: Optional[FusionHub] = None, name: str = "memo"):
        self.table = table
        self.hub = hub or default_hub()
        self.name = name
        self._table_state: Optional[MutableState] = None
        self._row_states: Dict[int, MutableState] = {}
        table.on_invalidate.append(self._on_invalidate)
        self._attached = True

    def detach(self) -> None:
        """Unsubscribe from the table and drop the leaf states. A bridge
        that outlives its consumers must be detached, or every
        ``table.invalidate`` keeps cascading into a graph nobody reads."""
        if self._attached:
            self._attached = False
            try:
                self.table.on_invalidate.remove(self._on_invalidate)
            except ValueError:
                pass
            self._row_states.clear()
            self._table_state = None

    # ------------------------------------------------------------------ deps
    async def use_table(self) -> int:
        """Register a whole-table dependency on the ambient computing node;
        returns the table version."""
        if self._table_state is None:
            self._table_state = MutableState(
                self.table.version, self.hub, name=f"{self.name}-table"
            )
        return await self._table_state.use()

    async def use_rows(self, ids: Iterable[int]) -> None:
        """Register per-row dependencies on the ambient computing node."""
        for i in ids:
            i = int(i)
            state = self._row_states.get(i)
            if state is None:
                state = self._row_states[i] = MutableState(
                    self.table.version, self.hub, name=f"{self.name}-row{i}"
                )
            await state.use()

    # ------------------------------------------------------------------ cascade
    def _on_invalidate(self, ids: np.ndarray) -> None:
        version = self.table.version
        if self._table_state is not None:
            self._table_state.set(version)
        row_states = self._row_states
        if row_states:
            if len(ids) < len(row_states):
                hits = (row_states.get(int(i)) for i in ids)
            else:
                id_set = set(int(i) for i in ids)
                hits = (s for i, s in row_states.items() if i in id_set)
            for state in hits:
                if state is not None:
                    state.set(version)

    def live_row_leaves(self) -> int:
        return len(self._row_states)
