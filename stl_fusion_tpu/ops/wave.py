"""The invalidation wave — batched sparse-BFS frontier expansion, jitted.

This is the TPU-native replacement for the reference's invalidation hot path:
``Computed.Invalidate()``'s synchronous, lock-per-node, pointer-chasing DFS
over ``_usedBy`` edge sets (src/Stl.Fusion/Computed.cs:162-230, cascade at
210-217). Instead of chasing pointers, the dependency graph lives in HBM as
an edge-parallel CSR-style structure and a whole *batch* of seed
invalidations expands level-by-level:

    frontier_{k+1}[d] = OR over edges (s→d): frontier_k[s]
                        AND node_epoch[d] == edge_dst_epoch   (version match)
                        AND NOT invalid[d]

Version-consistent edges: the reference stores ``(input, version)`` in
_usedBy and only fires on version match (Computed.cs:213-215). On device the
version is an int32 per-node *epoch* bumped on every recompute; an edge
carries the dependent's epoch at capture time, so stale edges (left by the
pruner-tolerant design) never re-invalidate a recomputed node.

Shapes are static (padded capacities) so XLA compiles one program: gathers +
scatter-max per level inside ``lax.while_loop``. Every op maps onto TPU VPU
lanes + HBM streaming; no host round-trips inside a wave.

Layout (all int32, device-resident):
- ``edge_src[e]``   — the used node (invalidation source); padding = n_cap
- ``edge_dst[e]``   — the dependent; padding = n_cap (a dummy slot)
- ``edge_dst_epoch[e]`` — dependent's epoch at edge-capture; padding = -1
- ``node_epoch[i]`` — current epoch per node; the dummy slot holds -2
- ``invalid[i]``    — invalidated flag (bool)

The arrays are sized (n_cap+1,) so the dummy slot absorbs padded-edge
gathers/scatters without branches.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "GraphArrays",
    "wave_step",
    "run_wave",
    "run_wave_collect",
    "run_wave_with_stats",
    "run_waves_chained",
    "run_waves_union",
    "seeds_to_frontier",
]


class GraphArrays(NamedTuple):
    """Device-resident dependency-graph mirror (see module docstring)."""

    edge_src: jax.Array  # int32[e_cap]
    edge_dst: jax.Array  # int32[e_cap]
    edge_dst_epoch: jax.Array  # int32[e_cap]
    node_epoch: jax.Array  # int32[n_cap+1]
    invalid: jax.Array  # bool[n_cap+1]

    @property
    def n_cap(self) -> int:
        return self.node_epoch.shape[0] - 1

    @property
    def e_cap(self) -> int:
        return self.edge_src.shape[0]


def seeds_to_frontier(n_cap: int, seed_ids: jax.Array) -> jax.Array:
    """Seed id list (padded with -1) → boolean frontier of size n_cap+1."""
    frontier = jnp.zeros(n_cap + 1, dtype=jnp.bool_)
    safe = jnp.where(seed_ids >= 0, seed_ids, n_cap)
    return frontier.at[safe].set(True).at[n_cap].set(False)


def wave_step(
    frontier: jax.Array, g: GraphArrays
) -> Tuple[jax.Array, GraphArrays]:
    """One BFS level: expand ``frontier`` across all version-matched edges."""
    src_active = frontier[g.edge_src]  # gather
    dst_epoch_now = g.node_epoch[g.edge_dst]  # gather
    fire = src_active & (dst_epoch_now == g.edge_dst_epoch) & ~g.invalid[g.edge_dst]
    next_frontier = (
        jnp.zeros_like(frontier).at[g.edge_dst].max(fire).at[g.n_cap].set(False)
    )
    invalid = g.invalid | next_frontier
    return next_frontier, g._replace(invalid=invalid)


@functools.partial(jax.jit, donate_argnums=(1,))
def run_wave(seed_frontier: jax.Array, g: GraphArrays) -> Tuple[GraphArrays, jax.Array]:
    """Full cascading-invalidation wave from a seed frontier.

    Returns (updated graph, newly-invalidated count). The while_loop runs
    entirely on device; levels continue until the frontier empties.
    """
    # seeds invalidate unconditionally (they're the nodes invalidate() was
    # called on), but already-invalid seeds don't re-expand
    fresh_seeds = seed_frontier & ~g.invalid
    g = g._replace(invalid=g.invalid | fresh_seeds)
    return _expand_to_fixpoint(fresh_seeds, g)


def _expand_to_fixpoint(fresh_seeds: jax.Array, g: GraphArrays):
    """Shared wave loop: expand fresh (already-marked) seeds until empty.
    Returns (g, newly-invalidated count incl. the seeds)."""

    def cond(carry):
        frontier, _g, _count = carry
        return frontier.any()

    def body(carry):
        frontier, g, count = carry
        nxt, g = wave_step(frontier, g)
        return nxt, g, count + nxt.sum(dtype=jnp.int32)

    _f, g, count = lax.while_loop(
        cond, body, (fresh_seeds, g, fresh_seeds.sum(dtype=jnp.int32))
    )
    return g, count


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
def run_wave_collect(
    seed_frontier: jax.Array, g: GraphArrays, cap: int
) -> Tuple[GraphArrays, jax.Array, jax.Array, jax.Array]:
    """run_wave that also COMPACTS the newly-invalidated node ids on device.

    Returns (g, count, ids: int32[cap] padded with -1, overflow: bool).
    The live path (graph/backend.py) reads back only ``count`` and the id
    buffer — O(wave size), not O(graph size) — instead of diffing two full
    invalid-mask snapshots on host (the r1 design VERDICT.md weak #2).
    When ``count > cap`` the buffer holds the first ``cap`` ids by node id
    and ``overflow`` is set; the caller falls back to a mask readback.
    """
    inv_before = g.invalid
    fresh_seeds = seed_frontier & ~g.invalid
    g = g._replace(invalid=g.invalid | fresh_seeds)
    g, count = _expand_to_fixpoint(fresh_seeds, g)
    newly = g.invalid & ~inv_before
    pos = jnp.cumsum(newly.astype(jnp.int32)) - 1
    scatter_pos = jnp.where(newly & (pos < cap), pos, cap)  # OOB → dropped
    ids = (
        jnp.full(cap, -1, dtype=jnp.int32)
        .at[scatter_pos]
        .set(jnp.arange(newly.shape[0], dtype=jnp.int32), mode="drop")
    )
    return g, count, ids, count > cap


@functools.partial(jax.jit, donate_argnums=(1,))
def run_waves_chained(
    seed_ids_mat: jax.Array, g: GraphArrays
) -> Tuple[GraphArrays, jax.Array, jax.Array]:
    """Chain W seed-id waves (int32[W, S], -1-padded) in ONE program.

    Each wave cascades over the state the previous one left (the live
    burst shape: many commands completing back-to-back get ONE dispatch +
    ONE readback instead of W relay round trips). Returns
    (g, per-wave newly-invalidated counts int32[W], union newly mask).
    """
    inv_before = g.invalid
    n_cap = g.n_cap

    def body(g, seed_ids):
        fresh = seeds_to_frontier(n_cap, seed_ids) & ~g.invalid
        g = g._replace(invalid=g.invalid | fresh)
        g, count = _expand_to_fixpoint(fresh, g)
        return g, count

    g, counts = lax.scan(body, g, seed_ids_mat)
    return g, counts, g.invalid & ~inv_before


@functools.partial(jax.jit, donate_argnums=(1,))
def run_waves_union(
    seed_ids: jax.Array, g: GraphArrays
) -> Tuple[GraphArrays, jax.Array, jax.Array]:
    """Union cascade: ALL seeds (int32[...], -1-padded) expand in ONE BFS.

    Invalidation is idempotent and the live batch path applies only the
    UNION of newly-invalid nodes (graph/backend.py::invalidate_cascade_batch
    reads counts.sum() + the union mask) — so chaining W sequential waves
    (O(edges × depth × W), which at 1M nodes × 64 waves ran long enough to
    get the TPU worker killed mid-program) collapses to one expansion,
    O(edges × depth) total. Returns (g, newly count, union newly mask).

    Seeds CONDUCT even when already invalid (r4): a host-led columnar mark
    (``table.invalidate`` → icasc journal entry) sets a row's invalid bit
    without the host having walked its DEVICE-ONLY declared dependents, so
    the expansion from such a seed must still fire them. Already-invalid
    NON-seed nodes keep blocking propagation — they were either cascaded
    when they were invalidated, or they are seeds of this same batch.
    Pre-invalid seeds don't count as newly (mask diff vs inv_before).
    """
    inv_before = g.invalid
    frontier = seeds_to_frontier(g.n_cap, seed_ids.reshape(-1))
    g = g._replace(invalid=g.invalid | frontier)
    g, _ = _expand_to_fixpoint(frontier, g)
    newly = g.invalid & ~inv_before
    return g, newly.sum(dtype=jnp.int32), newly


@functools.partial(jax.jit, donate_argnums=(1,))
def run_wave_with_stats(
    seed_frontier: jax.Array, g: GraphArrays
) -> Tuple[GraphArrays, jax.Array, jax.Array]:
    """run_wave + BFS depth (levels executed) for latency analysis."""
    fresh_seeds = seed_frontier & ~g.invalid
    g = g._replace(invalid=g.invalid | fresh_seeds)

    def cond(carry):
        frontier, _g, _count, _depth = carry
        return frontier.any()

    def body(carry):
        frontier, g, count, depth = carry
        nxt, g = wave_step(frontier, g)
        # depth = productive levels (the final empty expansion doesn't count)
        return nxt, g, count + nxt.sum(dtype=jnp.int32), depth + nxt.any().astype(jnp.int32)

    frontier, g, count, depth = lax.while_loop(
        cond,
        body,
        (fresh_seeds, g, fresh_seeds.sum(dtype=jnp.int32), jnp.int32(0)),
    )
    return g, count, depth
