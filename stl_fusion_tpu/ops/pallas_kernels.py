"""Hand-written Pallas TPU kernels for the invalidation hot path.

Two kernels live here (the rest of the wave pipeline deliberately stays in
XLA — its gathers/scatters are already near-optimal and fuse well):

- :func:`or_popcount` — the wave FINALIZER: merge a new invalidation bit
  vector into the accumulated one and count newly-lit bits, in ONE pass
  over the words (XLA materializes ``new & ~old`` as an intermediate before
  the reduce unless it fuses; here merge + delta-popcount + scalar
  accumulation share a single VMEM-resident tile walk).
- :func:`make_ring_all_gather` — the per-level frontier exchange as an
  explicit ICI ring: each device forwards its bit-packed frontier words
  around a logical ring with double-buffered RDMA
  (``pltpu.make_async_remote_copy``), the guide's ring-collective pattern.
  This is the kernel form of SURVEY §5.8's "intra-pod invalidation fan-out
  = ICI all-gather of per-host frontier buffers"; ``lax.all_gather`` stays
  the default (XLA's collective scheduler overlaps it fine), the ring
  kernel is for meshes where the frontier exchange needs manual overlap
  control.

Both kernels auto-fall back to interpreter mode off-TPU so the CPU-mesh
test suite exercises their logic; on-chip they compile via Mosaic.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["or_popcount", "make_ring_all_gather"]

_LANES = 128
_BLOCK_ROWS = 256  # 256x128 int32 = 128 KiB per buffer — 3 buffers well under VMEM


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------- or+popcount
def _or_popcount_kernel(new_ref, old_ref, merged_ref, count_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        count_ref[0, 0] = 0

    new = new_ref[...]
    old = old_ref[...]
    merged_ref[...] = new | old
    delta = lax.population_count(new & ~old)
    count_ref[0, 0] += jnp.sum(delta)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _or_popcount_2d(new2d, old2d, interpret: bool):
    rows = new2d.shape[0]
    grid = rows // _BLOCK_ROWS
    block = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    merged, count = pl.pallas_call(
        _or_popcount_kernel,
        grid=(grid,),
        in_specs=[block, block],
        out_specs=[
            block,
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(new2d.shape, jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=interpret,
    )(new2d, old2d)
    return merged, count[0, 0]


def or_popcount(new_bits, old_bits, interpret: Optional[bool] = None):
    """``(old | new, popcount(new & ~old))`` over int32 bit-vector words.

    1-D int32 inputs of equal length; zero-pads internally to the kernel
    tile. Returns (merged 1-D array, newly-lit bit count as 0-d int32).
    """
    if interpret is None:
        interpret = not _on_tpu()
    n = new_bits.shape[0]
    tile = _BLOCK_ROWS * _LANES
    n_pad = (n + tile - 1) // tile * tile
    new2d = jnp.zeros(n_pad, jnp.int32).at[:n].set(new_bits).reshape(-1, _LANES)
    old2d = jnp.zeros(n_pad, jnp.int32).at[:n].set(old_bits).reshape(-1, _LANES)
    merged, count = _or_popcount_2d(new2d, old2d, interpret)
    return merged.reshape(-1)[:n], count


# ---------------------------------------------------------------- ring gather
def _ring_kernel(local_ref, out_ref, comm_ref, send_sem, recv_sem, *, axis: str):
    n_dev = lax.axis_size(axis)
    my_id = lax.axis_index(axis)
    chunk = local_ref.shape[0]

    # slot my own chunk into the gathered output
    out_ref[pl.ds(my_id * chunk, chunk), :] = local_ref[...]
    comm_ref[0] = local_ref[...]

    def step_body(step, _):
        send_slot = step % 2
        recv_slot = 1 - send_slot
        dst = lax.rem(my_id + 1, n_dev)
        src_owner = lax.rem(my_id - step - 1 + 2 * n_dev, n_dev)

        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        out_ref[pl.ds(src_owner * chunk, chunk), :] = comm_ref[recv_slot]
        return 0

    lax.fori_loop(0, n_dev - 1, step_body, 0)


def ring_all_gather_supported() -> bool:
    """The ring kernel leans on newer-jax APIs (``lax.axis_size``, varying
    manual-axes ShapeDtypeStructs); older jax runs every other exchange
    but must DECLINE this one loudly instead of failing mid-trace."""
    import inspect

    import jax as _jax

    try:
        return hasattr(lax, "axis_size") and "vma" in inspect.signature(
            _jax.ShapeDtypeStruct
        ).parameters
    except (TypeError, ValueError):
        return False


def make_ring_all_gather(axis: str, interpret: Optional[bool] = None):
    """A shard_map-inner ``all_gather(..., tiled=True)`` replacement.

    Returns ``ring(local_words)`` for use INSIDE ``shard_map``: takes this
    device's uint32 frontier words ``(chunk,)`` and returns the full
    ``(n_dev * chunk,)`` gathered vector, moved hop-by-hop over the ICI
    ring with double-buffered RDMA. ``chunk`` must be a multiple of 128.
    """
    if not ring_all_gather_supported():
        raise NotImplementedError(
            "the Pallas ring all-gather needs lax.axis_size + vma-aware "
            "ShapeDtypeStruct (newer jax); use exchange='packed'/'bool'"
        )
    if interpret is None:
        interpret = not _on_tpu()

    def ring(local_words):
        chunk = local_words.shape[0]
        assert chunk % _LANES == 0, "ring chunk must be a multiple of 128 lanes"
        rows = chunk // _LANES
        local2d = local_words.reshape(rows, _LANES).astype(jnp.uint32)
        n_dev_static = lax.axis_size(axis)  # static for a bound mesh axis
        out = pl.pallas_call(
            functools.partial(_ring_kernel, axis=axis),
            out_shape=jax.ShapeDtypeStruct(
                (n_dev_static * rows, _LANES), jnp.uint32, vma=frozenset({axis})
            ),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2, rows, _LANES), jnp.uint32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True, collective_id=7
            ),
            interpret=interpret,
        )(local2d)
        return out.reshape(-1)

    return ring
