"""Bit-packed pull-mode waves: 32 invalidation cascades per pass.

The throughput endgame of the wave kernel family (see ell_wave.py for the
work-efficient single-wave path). Two ideas compose:

1. **Pull mode.** Level expansion reads each node's IN-list ("which nodes do
   I depend on — did any of them just fire?"). In-degree is naturally small
   (a compute method uses a handful of others; the synthetic DAG uses ~3),
   and `build_ell` on the REVERSED edge list bounds it at k with virtual
   OR-collector nodes. Per level the ONLY arbitrary-indexed access is
   ``frontier[in_src]``; the version check (edge epoch vs own epoch),
   fire combination, and invalid update are all contiguous vector ops —
   exactly what the TPU VPU streams at full HBM bandwidth.

2. **Bit-packing.** Invalidation is idempotent and commutative, so 32
   INDEPENDENT waves (32 command completions, in reference terms — the
   OperationCompletionNotifier queue processed SIMD instead of serially)
   ride one int32 lane: bit w = "wave w reached this node". The per-index
   gather cost — the TPU's weak spot — is amortized 32×.

Wave depth becomes max over the batch, and all 32 waves share one epoch
snapshot (graph consistent at batch start) — the batching contract.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

from .ell_wave import EllGraph, build_ell

__all__ = ["build_pull_graph", "build_pull_wave32", "seeds_to_bits"]


def build_pull_graph(src: np.ndarray, dst: np.ndarray, n_nodes: int, k: int = 8) -> EllGraph:
    """In-edge ELL: row d lists the nodes d depends on (virtual OR-collectors
    bound fan-in at k). Just build_ell on the reversed edges."""
    return build_ell(dst, src, n_nodes, k=k)


def seeds_to_bits(n_tot: int, seed_ids_per_wave) -> np.ndarray:
    """List of ≤32 seed-id arrays → int32 bitmask vector (host-side prep)."""
    bits = np.zeros(n_tot + 1, dtype=np.int32)
    for w, ids in enumerate(seed_ids_per_wave[:32]):
        bits[np.asarray(ids, dtype=np.int64)] |= np.int32(1 << w) if w < 31 else np.int32(-(1 << 31))
    bits[n_tot] = 0
    return bits


def build_pull_wave32(graph: EllGraph):
    """Compile the 32-wave bit-packed cascade.

    Returns (state0, wave32) where
    ``wave32(seed_bits, state) -> (state, real_invalidation_count)``:
    ``seed_bits`` is int32[n_tot+1]; the count sums popcounts over REAL nodes
    (virtual collectors excluded) across all 32 waves.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_tot = graph.n_tot
    in_src = jnp.asarray(graph.ell_dst)  # (n_tot+1, k): row d's dependencies
    edge_epoch = jnp.asarray(graph.ell_epoch)
    is_real = jnp.asarray(graph.is_real)

    class PullState(NamedTuple):
        node_epoch: jax.Array  # int32[n_tot+1]
        invalid_bits: jax.Array  # int32[n_tot+1]

    def init_state():
        return PullState(
            jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2),
            jnp.zeros(n_tot + 1, dtype=jnp.int32),
        )

    @jax.jit
    def wave32(seed_bits: jax.Array, state):
        node_epoch, invalid = state.node_epoch, state.invalid_bits
        live = edge_epoch == node_epoch[:, None]  # (n_tot+1, k) contiguous
        frontier = seed_bits & ~invalid
        invalid = invalid | frontier

        def cond(carry):
            frontier, _inv, go = carry
            return go

        k = in_src.shape[1]

        def body(carry):
            frontier, invalid, _go = carry
            f = frontier[in_src]  # (n_tot+1, k) — the one arbitrary gather
            contrib = jnp.where(live, f, 0)
            fire = contrib[:, 0]
            for j in range(1, k):  # static small k: unrolled OR-fold
                fire = fire | contrib[:, j]
            fire = (fire & ~invalid).at[n_tot].set(0)
            invalid = invalid | fire
            return fire, invalid, (fire != 0).any()

        _f, invalid, _go = lax.while_loop(cond, body, (frontier, invalid, (frontier != 0).any()))
        counts = lax.population_count(jnp.where(is_real, invalid, 0))
        return PullState(node_epoch, invalid), counts.sum(dtype=jnp.int32)

    return init_state(), wave32
