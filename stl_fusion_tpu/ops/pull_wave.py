"""Bit-packed pull-mode waves: 32 invalidation cascades per pass.

The throughput endgame of the wave kernel family (see ell_wave.py for the
work-efficient single-wave path). Two ideas compose:

1. **Pull mode.** Level expansion reads each node's IN-list ("which nodes do
   I depend on — did any of them just fire?"). In-degree is naturally small
   (a compute method uses a handful of others; the synthetic DAG uses ~3),
   and `build_ell` on the REVERSED edge list bounds it at k with virtual
   OR-collector nodes. Per level the ONLY arbitrary-indexed access is
   ``frontier[in_src]``; the version check (edge epoch vs own epoch),
   fire combination, and invalid update are all contiguous vector ops —
   exactly what the TPU VPU streams at full HBM bandwidth.

2. **Bit-packing.** Invalidation is idempotent and commutative, so 32
   INDEPENDENT waves (32 command completions, in reference terms — the
   OperationCompletionNotifier queue processed SIMD instead of serially)
   ride one int32 lane: bit w = "wave w reached this node". The per-index
   gather cost — the TPU's weak spot — is amortized 32×.

Wave depth becomes max over the batch, and all 32 waves share one epoch
snapshot (graph consistent at batch start) — the batching contract.

The graph arrays travel as RUNTIME ARGUMENTS (``PullGraphArrays``), never
as jit closure captures: at 10M nodes the in-edge table is ~320MB, and a
closure capture would embed it as an HLO constant — blowing up the compile
payload (and this environment's remote-compile relay rejects it outright).
Passing them as device-resident args keeps the compiled program
shape-parameterized and the upload a one-time ``device_put``.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

from .ell_wave import EllGraph, build_ell

__all__ = [
    "pack_lane_matrix",
    "PullGraphArrays",
    "PullState",
    "build_pull_graph",
    "build_pull_wave32",
    "pull_wave32_step",
    "pull_graph_arrays",
    "pull_init_state",
    "seeds_to_bits",
]


def build_pull_graph(src: np.ndarray, dst: np.ndarray, n_nodes: int, k: int = 8) -> EllGraph:
    """In-edge ELL: row d lists the nodes d depends on (virtual OR-collectors
    bound fan-in at k). Just build_ell on the reversed edges."""
    return build_ell(dst, src, n_nodes, k=k)


def pack_seed_words(
    n_rows: int, seed_ids_per_wave, words: int = 1, id_map: "np.ndarray" = None
) -> np.ndarray:
    """≤``32*words`` seed-id lists → int32 bit words (host-side prep):
    1-D [n_rows] for ``words=1``, else [n_rows, words]. ``id_map`` remaps
    seed ids first (e.g. topo's original→level-order permutation). The
    shared packer behind every bit-packed kernel's seed path."""
    bits = np.zeros((n_rows, words), dtype=np.int32)
    for i, ids in enumerate(seed_ids_per_wave[: 32 * words]):
        w, lane = divmod(i, 32)
        ids = np.asarray(ids, dtype=np.int64)
        if id_map is not None:
            ids = id_map[ids]
        bits[ids, w] |= np.int32(1 << lane) if lane < 31 else np.int32(-(1 << 31))
    return bits[:, 0] if words == 1 else bits


def pack_lane_matrix(groups, pad_id: int, n_valid: int, id_map=None, base_index: int = 0):
    """Per-group seed ids → (int32[32*words, width] lane matrix, words):
    row i holds group i's UNIQUE ids (uniqueness matters — lane bits are
    scatter-ADDed on device), padded with ``pad_id``; words and width round
    up to powers of two so varying burst shapes reuse compiled programs.
    ``id_map`` optionally remaps ids (e.g. topo original→level-order); ids
    must lie in [0, n_valid). THE shared packer behind both lane-burst
    faces (DeviceGraph.run_waves_lanes, PackedShardedGraph.run_gated_lanes)."""
    words = 1
    while words < (len(groups) + 31) // 32:
        words <<= 1
    width = 1
    while width < max((len(s) for s in groups), default=1):
        width <<= 1
    mat = np.full((32 * words, width), pad_id, dtype=np.int32)
    for i, s in enumerate(groups):
        ids = np.unique(np.asarray(s, dtype=np.int64))
        if len(ids) and (ids[0] < 0 or ids[-1] >= n_valid):
            raise ValueError(
                f"group {base_index + i}: seed ids must be in [0, {n_valid})"
            )
        if id_map is not None:
            ids = id_map[ids]
        mat[i, : len(ids)] = ids.astype(np.int32)
    return mat, words


def seeds_to_bits(n_tot: int, seed_ids_per_wave) -> np.ndarray:
    """List of ≤32 seed-id arrays → int32 bitmask vector (host-side prep)."""
    bits = pack_seed_words(n_tot + 1, seed_ids_per_wave)
    bits[n_tot] = 0
    return bits


class PullGraphArrays(NamedTuple):
    """Device-resident graph structure, passed to the kernel per call."""

    in_src: "object"  # int32[n_tot+1, k]: row d's dependencies
    edge_epoch: "object"  # int32[n_tot+1, k]: captured dependency epochs
    is_real: "object"  # bool[n_tot+1]: False for virtual OR-collectors


class PullState(NamedTuple):
    node_epoch: "object"  # int32[n_tot+1]
    invalid_bits: "object"  # int32[n_tot+1]


def pull_graph_arrays(graph: EllGraph) -> PullGraphArrays:
    """One-time upload of the packed in-edge table to device HBM."""
    import jax.numpy as jnp

    return PullGraphArrays(
        in_src=jnp.asarray(graph.ell_dst),
        edge_epoch=jnp.asarray(graph.ell_epoch),
        is_real=jnp.asarray(graph.is_real),
    )


def pull_init_state(n_tot: int) -> PullState:
    import jax.numpy as jnp

    return PullState(
        jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2),
        jnp.zeros(n_tot + 1, dtype=jnp.int32),
    )


def _pull_wave32_impl(garrays: PullGraphArrays, seed_bits, state: PullState):
    import jax.numpy as jnp
    from jax import lax

    in_src, edge_epoch, is_real = garrays
    n_tot = in_src.shape[0] - 1
    k = in_src.shape[1]

    node_epoch, invalid = state.node_epoch, state.invalid_bits
    live = edge_epoch == node_epoch[:, None]  # (n_tot+1, k) contiguous
    frontier = seed_bits & ~invalid
    invalid = invalid | frontier

    def cond(carry):
        _frontier, _inv, go = carry
        return go

    def body(carry):
        frontier, invalid, _go = carry
        f = frontier[in_src]  # (n_tot+1, k) — the one arbitrary gather
        contrib = jnp.where(live, f, 0)
        fire = contrib[:, 0]
        for j in range(1, k):  # static small k: unrolled OR-fold
            fire = fire | contrib[:, j]
        fire = (fire & ~invalid).at[n_tot].set(0)
        invalid = invalid | fire
        return fire, invalid, (fire != 0).any()

    _f, invalid, _go = lax.while_loop(cond, body, (frontier, invalid, (frontier != 0).any()))
    counts = lax.population_count(jnp.where(is_real, invalid, 0))
    return PullState(node_epoch, invalid), counts.sum(dtype=jnp.int32)


@functools.lru_cache(maxsize=1)
def pull_wave32_step():
    """The jitted 32-wave kernel: ``step(garrays, seed_bits, state)``.

    Module-level (cached) so composing programs — e.g. the benchmark's
    lax.scan over seed batches — can call it inside their own jit while
    threading ``garrays`` through as parameters.
    """
    import jax

    return jax.jit(_pull_wave32_impl)


def build_pull_wave32(graph: EllGraph):
    """Compile the 32-wave bit-packed cascade for one graph.

    Returns (state0, wave32) where
    ``wave32(seed_bits, state) -> (state, real_invalidation_count)``:
    ``seed_bits`` is int32[n_tot+1]; the count sums popcounts over REAL nodes
    (virtual collectors excluded) across all 32 waves. The device graph is
    exposed as ``wave32.garrays`` (and the raw kernel as ``wave32.step``)
    for callers that fuse the wave into a larger jitted program.
    """
    garrays = pull_graph_arrays(graph)
    step = pull_wave32_step()

    def wave32(seed_bits, state):
        return step(garrays, seed_bits, state)

    wave32.garrays = garrays
    wave32.step = step
    wave32.impl = _pull_wave32_impl
    return pull_init_state(graph.n_tot), wave32
