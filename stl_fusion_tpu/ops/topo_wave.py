"""Topo-ordered single-sweep 32-wave kernel: the whole cascade in ONE pass.

The level-synchronized kernels (pull_wave.py, hybrid_wave.py) pay a gather
over the in-edge table EVERY BFS level — O(n·k · depth) gathered words per
32-wave batch. But the dependency graph is a DAG (a computed value can only
depend on values that existed when it was computed — Computed.cs:347-363
"dependencies that didn't finish aren't dependencies"), so there is a
strictly better schedule:

1. **Topological level ordering** (host/native, once per graph build).
   level[d] = 1 + max(level of d's dependencies); renumber nodes so each
   level occupies a contiguous id range. All in-edges then point to strictly
   LOWER levels.
2. **Single sweep.** Process levels in ascending order inside one jitted
   program: level l's rows gather ``invalid`` at their in-slots — which are
   all in already-finalized earlier levels — OR-fold, and write the level's
   contiguous slice. After one pass over the table, ``invalid`` holds the
   full transitive closure of all 32 packed waves, no matter where their
   seeds sat. Total gathered words = n·k, not n·k·depth: depth× less HBM
   traffic than the dense pull kernel (the bench DAG runs ~30 levels).

Level boundaries are STATIC (baked into the compiled program — they only
change when the graph's level structure changes), while the table contents
remain runtime args, so edge/epoch updates that preserve the level layout
need no recompile and the compile payload stays shape-only (see
pull_wave.py on why the arrays must not ride the payload).

Pull-mode bonus (see pull_wave.py): hub fan-OUT never matters — only
in-degree is bounded (avg ~3 in the bench DAG) — so the augmented graph has
few or no virtual collector nodes and real depth stays shallow.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import numpy as np

from .ell_wave import EllGraph, build_ell
from .pull_wave import pack_seed_words

__all__ = [
    "TopoGraph",
    "TopoGraphArrays",
    "TopoState",
    "build_topo_graph",
    "topo_graph_arrays",
    "topo_init_state",
    "build_topo_wave32",
    "topo_mirror_gate_step",
    "topo_mirror_finish_step",
    "topo_mirror_fused_union_step",
    "topo_mirror_fused_lanes_step",
    "topo_mirror_fused_lanes_chain_step",
    "topo_mirror_superround_step",
    "topo_mirror_gate_lanes_step",
    "topo_mirror_finish_lanes_step",
    "run_topo_sweep_passes",
    "topo_seeds_to_bits",
]


class TopoGraph(NamedTuple):
    """Host-built in-ELL in topological level order.

    Row ids are NEW (level-ordered) ids; ``perm`` maps new→old augmented
    ids, ``inv_perm`` old→new (both length n_tot+1, fixed point at the null
    row n_tot).
    """

    in_src: np.ndarray  # int32[n_tot+1, k] — NEW-id in-neighbors; pad n_tot
    edge_epoch: np.ndarray  # int32[n_tot+1, k] — captured epochs; pad -1
    is_real: np.ndarray  # bool[n_tot+1] (new order)
    level_starts: Tuple[int, ...]  # len L+1; level l = rows [starts[l], starts[l+1])
    perm: np.ndarray  # int64[n_tot+1]: new id -> old id
    inv_perm: np.ndarray  # int64[n_tot+1]: old id -> new id
    n_real: int
    n_tot: int
    k: int


def _levels_numpy(in_src: np.ndarray, n: int, k: int) -> np.ndarray:
    """Longest-path levels by vectorized relaxation (fallback; the native
    Kahn pass in graphpack.cpp::gp_topo_levels is the fast path)."""
    level = np.zeros(n, dtype=np.int32)
    table = in_src[:n].astype(np.int64)
    live = table < n
    safe = np.where(live, table, 0)
    for _ in range(4 * n + 4):  # depth is bounded by n
        cand = np.where(live, level[safe] + 1, 0).max(axis=1).astype(np.int32)
        if (cand <= level).all():
            return level
        level = np.maximum(level, cand)
    raise ValueError("level relaxation failed to converge (cycle?)")


def _quantize_level(s: int) -> int:
    """Pad a level's row count up to a coarse size bucket (≤12.5% overhead
    past 128 rows, minimum grid 16). Level sizes — and therefore the
    ``level_starts`` tuple the sweep program is keyed on — become STABLE
    under small structural drift: a mirror rebuild after churn usually
    produces the SAME tuple and reuses the already-compiled sweep (in-
    process lru + persistent cache) instead of paying a full XLA compile
    (~3 min at 1M nodes) inside the serving path."""
    if s <= 0:
        return 0
    if s <= 16:
        return 16
    grid = max(16, 1 << (int(s - 1).bit_length() - 3))
    return -(-s // grid) * grid


def build_topo_graph(
    src: np.ndarray, dst: np.ndarray, n_nodes: int, k: int = 4, use_native: bool = True,
    quantize: bool = True, slack: int = 0,
) -> TopoGraph:
    """In-ELL (build_ell on reversed edges, bounding in-degree at k with
    virtual OR-collectors) renumbered into topological level order, each
    level padded to a quantized size (null rows: no in-edges, not real) so
    the compiled sweep survives rebuilds — see :func:`_quantize_level`.

    ``slack`` appends that many GUARANTEED-FREE pad columns to every row:
    the live mirror's patch path needs a free slot to splice a new in-edge
    in place, and a packed row (in-degree ≡ k) would otherwise break the
    patch log on the first realistic-churn edge landing on it. Slack
    widens the sweep's row gathers by slack/k — the live mirror pays it,
    the static bench (slack=0) does not."""
    ell: EllGraph = build_ell(dst, src, n_nodes, k=k, use_native=use_native)
    n_tot_o = ell.n_tot
    level = None
    if use_native:
        from ..native import native_topo_levels

        level = native_topo_levels(ell.ell_dst, n_tot_o, k)
    if level is None:
        level = _levels_numpy(ell.ell_dst, n_tot_o, k)

    order = np.argsort(level, kind="stable")  # levels ascending over old ids
    sizes = np.bincount(level, minlength=int(level.max()) + 1 if n_tot_o else 1)
    padded = [(_quantize_level(int(s)) if quantize else int(s)) for s in sizes]
    n_tot = int(sum(padded))  # padded row-space size; null row at index n_tot
    if quantize and n_tot:
        # quantize the TOTAL too (≤ ~3% tail of pure null rows): programs
        # keyed on n_tot (gate/finish/lane epilogues) survive rebuilds whose
        # level structure drifted — the expensive 512-lane popcount epilogue
        # would otherwise recompile on every re-level. (n_tot == 0 — an
        # empty backend mirror — would shift by -1 here; the trivial graph
        # needs no padding at all.)
        grain = max(256, (1 << (n_tot.bit_length() - 1)) // 32)
        n_tot = -(-n_tot // grain) * grain

    # perm: new row -> old augmented id; pad rows map to the OLD null row
    # (their in-rows read as all-pad, epoch -1 — they can never fire)
    perm = np.full(n_tot + 1, n_tot_o, dtype=np.int64)
    starts = [0]
    pos = oi = 0
    for s, ps in zip(sizes, padded):
        s, ps = int(s), int(ps)
        perm[pos : pos + s] = order[oi : oi + s]
        oi += s
        pos += ps
        starts.append(pos)
    inv_perm = np.full(n_tot_o + 1, n_tot, dtype=np.int64)
    real = perm[:n_tot] != n_tot_o
    inv_perm[perm[:n_tot][real]] = np.nonzero(real)[0]
    inv_perm[n_tot_o] = n_tot

    # remap rows into new order and entries into new ids (the old pad row
    # maps to the new null row n_tot, so pad entries stay pads)
    in_src = inv_perm[ell.ell_dst[perm]].astype(np.int32)
    edge_epoch = ell.ell_epoch[perm]
    is_real = ell.is_real[perm] & (perm != n_tot_o)
    if slack:
        in_src = np.hstack(
            [in_src, np.full((in_src.shape[0], slack), n_tot, dtype=np.int32)]
        )
        edge_epoch = np.hstack(
            [edge_epoch, np.full((in_src.shape[0], slack), -1, dtype=np.int32)]
        )

    return TopoGraph(
        in_src, edge_epoch, is_real, tuple(starts), perm, inv_perm, n_nodes, n_tot,
        k + slack,
    )


class TopoGraphArrays(NamedTuple):
    in_src: "object"
    edge_epoch: "object"
    is_real: "object"


class TopoState(NamedTuple):
    node_epoch: "object"  # int32[n_tot+1] (new order)
    #: int32[n_tot+1] (words=1) or int32[n_tot+1, words] — each uint32 lane
    #: packs 32 independent waves; see topo_init_state(words=...)
    invalid_bits: "object"


@functools.lru_cache(maxsize=4)
def _derive_topo_epoch_kernel(n_tot: int):
    """Slot live ⇔ epoch 0, pad ⇔ -1: fully derivable from the id table —
    deriving ON DEVICE halves a mirror install's upload (the epoch table
    is as big as the structure table, ~264 MB at 10M through the relay)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def derive(in_src):
        return jnp.where(in_src != n_tot, 0, -1).astype(jnp.int32)

    return derive


def topo_graph_arrays(graph: TopoGraph) -> TopoGraphArrays:
    import jax.numpy as jnp

    in_src = jnp.asarray(graph.in_src)
    return TopoGraphArrays(
        in_src=in_src,
        edge_epoch=_derive_topo_epoch_kernel(graph.n_tot)(in_src),
        is_real=jnp.asarray(graph.is_real),
    )


def topo_init_state(n_tot: int, words: int = 1) -> TopoState:
    """``words`` packs ``32*words`` independent waves per sweep: the random
    row access that bounds the kernel fetches a full HBM transaction either
    way, so wider rows are nearly free throughput (32 B rows = 8 words)."""
    import jax.numpy as jnp

    if 32 * (n_tot + 1) >= 2**31:
        # per-word counts are summed in int32 on device (jax x64 is off);
        # beyond ~67M rows one word's count could silently wrap
        raise ValueError(
            f"topo sweep count tracking is int32-limited to <{2**31 // 32} rows; "
            f"got {n_tot + 1} — shard the graph (parallel/sharded_wave.py) instead"
        )
    shape = (n_tot + 1,) if words == 1 else (n_tot + 1, words)
    return TopoState(
        jnp.zeros(n_tot + 1, dtype=jnp.int32).at[n_tot].set(-2),
        jnp.zeros(shape, dtype=jnp.int32),
    )


def topo_seeds_to_bits(graph: TopoGraph, seed_ids_per_wave, words: int = 1) -> np.ndarray:
    """≤``32*words`` seed-id arrays (ORIGINAL node ids) → int32 bit
    vector[s] in NEW id space, ready for the sweep (1-D for ``words=1``,
    else [n_tot+1, words])."""
    bits = pack_seed_words(
        graph.n_tot + 1, seed_ids_per_wave, words=words, id_map=graph.inv_perm
    )
    bits[graph.n_tot] = 0
    return bits


def _topo_sweep_impl(
    level_starts, garrays: TopoGraphArrays, seed_bits, state: TopoState,
    start_level: int = 1,
):
    import jax.numpy as jnp
    from jax import lax

    in_src, edge_epoch, is_real = garrays
    n_tot = in_src.shape[0] - 1
    k = in_src.shape[1]

    node_epoch, invalid = state.node_epoch, state.invalid_bits
    # normalize to [n_tot+1, W]: W uint32 lanes = 32*W packed waves per pass
    squeeze = invalid.ndim == 1
    if squeeze:
        invalid = invalid[:, None]
    if seed_bits.ndim == 1:
        seed_bits = seed_bits[:, None]
    W = invalid.shape[1]
    if seed_bits.shape[1] != W:
        # broadcasting a mismatched width would silently duplicate seeds
        # into every lane (or drop lanes on the squeeze path)
        raise ValueError(
            f"seed_bits width {seed_bits.shape[1]} != state width {W}; "
            f"pass words= consistently to topo_seeds_to_bits/build_topo_wave32"
        )
    invalid_before = invalid
    invalid = (invalid | seed_bits).at[n_tot].set(0)

    # one pass, levels ascending: every gather reads only finalized rows.
    # start_level=1 skips level 0 (no in-edges at build time by definition);
    # multi-pass sweeps over PATCHED mirrors start at 0 — a patched edge
    # into a level-0 row (any edge into level 0 is a level violation) fires
    # from the previous pass's finalized state
    for l in range(start_level, len(level_starts) - 1):
        a, b = level_starts[l], level_starts[l + 1]
        if a == b:
            continue
        rows = lax.slice(in_src, (a, 0), (b, k))
        epochs = lax.slice(edge_epoch, (a, 0), (b, k))
        own = lax.slice(node_epoch, (a,), (b,))
        # dead edges (captured epoch != dependent's current epoch) read the
        # null row, whose word is always 0 (version-consistent edges,
        # Computed.cs:213-215)
        eff = jnp.where(epochs == own[:, None], rows, n_tot)
        f = invalid[eff]  # (b-a, k, W) gather from earlier levels
        fire = f[:, 0]
        for j in range(1, k):
            fire = fire | f[:, j]
        cur = lax.slice(invalid, (a, 0), (b, W))
        invalid = lax.dynamic_update_slice(invalid, cur | fire, (a, 0))

    newly = lax.population_count(
        jnp.where(is_real[:, None], invalid & ~invalid_before, 0)
    )
    # per-WORD counts: one word's count is ≤ 32*n (int32-safe); the total
    # across many packed waves can exceed int32, so callers sum in int64
    counts = newly.sum(axis=0, dtype=jnp.int32)
    if squeeze:
        invalid = invalid[:, 0]
        return TopoState(node_epoch, invalid), counts[0]
    return TopoState(node_epoch, invalid), counts


@functools.lru_cache(maxsize=8)
def topo_mirror_gate_step(n_tot: int):
    """Jitted burst PROLOGUE over a topo mirror: project the dense live
    invalid state into topo order (device gather — no host upload) and gate
    the seeds with dense-BFS semantics — an already-invalid node neither
    re-fires, counts, nor conducts (ops/wave.py::wave_step rule; a plain
    closure sweep over ``invalid | seeds`` would also propagate PRE-EXISTING
    invalidity, diverging from the dense path). The gate is expressed
    THROUGH the sweep's own epoch machinery so _topo_sweep_impl is reused
    verbatim: a blocked row gets epoch -3, so none of its in-edges (captured
    at epoch 0) version-match — it can never fire; its bit starts 0 and is
    never seeded, so nothing propagates THROUGH it either.

    Split from the sweep and the epilogue (:func:`topo_mirror_finish_step`)
    so the PASS COUNT of a patched mirror is a host loop over the jitted
    sweep — violations accumulating on a patched mirror never recompile
    anything (r4; the monolithic burst program re-traced per pass count)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gate(is_real, node_epoch0, perm_clipped, g_invalid, seed_new_ids):
        blocked = (
            jnp.where(is_real, g_invalid[perm_clipped], False)
            .astype(jnp.int32)
            .at[n_tot]
            .set(0)
        )
        node_epoch = jnp.where(blocked.astype(bool), -3, node_epoch0)
        # union seeds CONDUCT even when already invalid (see ops/wave.py
        # run_waves_union: an uncascaded columnar mark's declared dependents
        # exist only on device); blocked rows still can't RECEIVE (epoch -3)
        # and pre-invalid seeds are excluded from newly by the finish step
        seed_bits = (
            jnp.zeros(n_tot + 1, jnp.int32).at[seed_new_ids].set(1).at[n_tot].set(0)
        )
        return node_epoch, seed_bits

    return gate


@functools.lru_cache(maxsize=8)
def topo_mirror_finish_step(cap: int, n_tot: int):
    """Jitted burst EPILOGUE: count the newly-invalidated real rows from the
    final sweep bits, compact their ORIGINAL ids to ``cap`` (O(cap)
    readback), and scatter them back into the dense invalid array."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def finish(is_real, perm_clipped, g_invalid, final_bits):
        # ~pre-invalid: a conducting already-invalid seed is not NEWLY
        newly = final_bits.astype(bool) & is_real & ~g_invalid[perm_clipped]
        count = newly.sum(dtype=jnp.int32)
        pos = jnp.cumsum(newly.astype(jnp.int32)) - 1
        scatter_pos = jnp.where(newly & (pos < cap), pos, cap)  # OOB → dropped
        ids = (
            jnp.full(cap, -1, dtype=jnp.int32)
            .at[scatter_pos]
            .set(perm_clipped, mode="drop")
        )
        oob = g_invalid.shape[0]
        g_invalid2 = g_invalid.at[jnp.where(newly, perm_clipped, oob)].set(
            True, mode="drop"
        )
        return g_invalid2, count, ids, count > cap

    return finish


def run_topo_sweep_passes(level_starts, garrays, seed_bits, node_epoch, passes: int):
    """HOST loop over jitted sweep passes, chaining device state — the
    multi-pass execution of a patched mirror (level-violating edges need
    one extra pass each; see _try_patch_mirror). The sweep program is keyed
    only on level_starts: ANY pass count reuses it (start_level is pinned
    to 0 — level 0 is sources-only, so the extra slice is near-free, and a
    passes 1→2 transition must not re-key the program mid-serving: the
    compile would land inside a timed burst)."""
    import jax.numpy as jnp

    step = topo_sweep_step(level_starts, 0)
    state = TopoState(node_epoch, jnp.zeros_like(seed_bits))
    sb = seed_bits
    for _ in range(passes):
        state, _ = step(garrays, sb, state)
        sb = jnp.zeros_like(seed_bits)  # only the first pass seeds
    return state


def _sweep_adaptive(level_starts, garrays, seed_bits, state):
    """Adaptive pass mode (``passes <= 0``, ISSUE 17): one seeded sweep,
    then extra sweeps under a device-side ``lax.while_loop`` until the
    invalid bits reach a FIXED POINT. The bits are monotone under OR, so
    termination is guaranteed and the fixed point equals what any fixed
    pass count ≥ the true violation depth computes — the burst stops
    exactly when quiescent instead of paying a worst-case pass schedule
    on every dispatch (the fused-chain analogue of the routed plane's
    counted quiescence check)."""
    import jax.numpy as jnp
    from jax import lax

    state, _ = _topo_sweep_impl(level_starts, garrays, seed_bits, state, 0)
    zero_sb = jnp.zeros_like(seed_bits)

    def cond(carry):
        return carry[1]

    def body(carry):
        st, _changed = carry
        st2, _ = _topo_sweep_impl(level_starts, garrays, zero_sb, st, 0)
        return st2, (st2.invalid_bits != st.invalid_bits).any()

    state, _ = lax.while_loop(cond, body, (state, jnp.array(True)))
    return state


def _pack_bool_bits(mask):
    """Burst epilogues ship the newly-union as 1 bit/node through the
    per-byte-charged relay instead of capped id buffers + a separate pack
    dispatch (VERDICT r4 #2/#6); one shared definition in ops/bitops."""
    from .bitops import pack_bool_bits

    return pack_bool_bits(mask)


def _lane_counts_blocked(newly_bits, W: int, block: int = 1 << 15):
    """Per-lane popcounts of [rows, W] packed bits in ONE pass over HBM.

    The obvious ``stack([((bits[:, w] >> b) & 1).sum() ...])`` emits 32·W
    separate strided reductions which XLA does NOT fuse at scale — at 10M
    rows × W=16 that re-reads the 700 MB bit array hundreds of times
    (~30 s/burst measured). Here a fori_loop unpacks one [block, W, 32]
    tile at a time and accumulates [W, 32] partials: total traffic = one
    read of the bits + a 64 MB transient."""
    import jax.numpy as jnp
    from jax import lax

    rows = newly_bits.shape[0]
    nb = -(-rows // block)
    padded = jnp.pad(newly_bits, ((0, nb * block - rows), (0, 0)))
    shifts = jnp.arange(32, dtype=jnp.int32)[None, None, :]

    def body(i, acc):
        blk = lax.dynamic_slice(padded, (i * block, 0), (block, W))
        bits = (blk[:, :, None] >> shifts) & 1
        return acc + bits.sum(axis=0, dtype=jnp.int32)

    acc = lax.fori_loop(0, nb, body, jnp.zeros((W, 32), jnp.int32))
    return acc.reshape(W * 32)  # lane l = word l//32, bit l%32 — stack order


@functools.lru_cache(maxsize=8)
def topo_mirror_fused_union_step(
    level_starts: Tuple[int, ...], cap: int, n_tot: int, passes: int = 1
):
    """ONE-dispatch union burst (gate + sweep×passes + finish fused).

    Through a remote-relay environment every dispatch costs ~a round trip
    un-pipelined, so the split gate/sweep/finish pipeline pays 3-4 RTTs
    per lone wave. Small pass counts (a patched mirror carrying a few
    level violations — r5: one fused program per pass count ≤ 3, each
    compiled once per level layout and persisted) stay on the one-dispatch
    path; beyond that the split pipeline's host loop takes over so pass
    growth never recompiles anything."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def burst(garrays: TopoGraphArrays, node_epoch0, perm_clipped, g_invalid, seed_new_ids):
        is_real = garrays.is_real
        blocked = (
            jnp.where(is_real, g_invalid[perm_clipped], False)
            .astype(jnp.int32)
            .at[n_tot]
            .set(0)
        )
        node_epoch = jnp.where(blocked.astype(bool), -3, node_epoch0)
        seed_bits = (
            jnp.zeros(n_tot + 1, jnp.int32).at[seed_new_ids].set(1).at[n_tot].set(0)
        )
        state = TopoState(node_epoch, jnp.zeros(n_tot + 1, dtype=jnp.int32))
        if passes <= 0:
            state = _sweep_adaptive(level_starts, garrays, seed_bits, state)
        else:
            sb = seed_bits
            for _ in range(passes):
                state, _ = _topo_sweep_impl(level_starts, garrays, sb, state, 0)
                sb = jnp.zeros_like(seed_bits)  # only the first pass seeds
        newly = state.invalid_bits.astype(bool) & is_real & ~g_invalid[perm_clipped]
        count = newly.sum(dtype=jnp.int32)
        pos = jnp.cumsum(newly.astype(jnp.int32)) - 1
        scatter_pos = jnp.where(newly & (pos < cap), pos, cap)
        ids = (
            jnp.full(cap, -1, dtype=jnp.int32)
            .at[scatter_pos]
            .set(perm_clipped, mode="drop")
        )
        oob = g_invalid.shape[0]
        g_invalid2 = g_invalid.at[jnp.where(newly, perm_clipped, oob)].set(
            True, mode="drop"
        )
        return g_invalid2, count, ids, count > cap

    return burst


@functools.lru_cache(maxsize=8)
def topo_mirror_fused_lanes_step(
    level_starts: Tuple[int, ...], n_tot: int, words: int, passes: int = 1
):
    """ONE-dispatch lane burst (gate + sweep×``passes`` + finish fused) —
    see :func:`topo_mirror_fused_union_step` for the pass-count policy:
    small counts each get their own fused program (saving 2-3 relay round
    trips per burst), heavier violation loads fall to the split
    pipeline's host loop. The newly-union comes back as a
    device-packed DENSE bitmask (1 bit/node): burst unions at stress scale
    are millions of rows, so a capped id compaction overflowed every burst
    and cost a separate pack dispatch + mask diff (VERDICT r4 #2/#6)."""
    import jax
    import jax.numpy as jnp

    W = words

    @jax.jit
    def burst(garrays: TopoGraphArrays, node_epoch0, perm_clipped, g_invalid, seed_new_ids):
        g_invalid2, lane_counts, newly_dense = _lanes_stage_body(
            level_starts, n_tot, W, passes,
            garrays, node_epoch0, perm_clipped, g_invalid, seed_new_ids,
        )
        union_count = newly_dense.sum(dtype=jnp.int32)
        return g_invalid2, lane_counts, union_count, _pack_bool_bits(newly_dense)

    return burst


def _lanes_stage_body(
    level_starts, n_tot: int, W: int, passes: int,
    garrays: TopoGraphArrays, node_epoch0, perm_clipped, g_invalid, seed_new_ids,
):
    """One lane-burst stage against ``g_invalid`` (the shared body of the
    single-burst program and the chained scan below): gate → sweep×passes →
    newly accounting. Returns (g_invalid2, lane_counts, newly_dense)."""
    import jax.numpy as jnp

    L = 32 * W
    is_real = garrays.is_real
    blocked = (
        jnp.where(is_real, g_invalid[perm_clipped], False)
        .astype(jnp.int32)
        .at[n_tot]
        .set(0)
    )
    node_epoch = jnp.where(blocked.astype(bool), -3, node_epoch0)
    lanes = jnp.arange(L, dtype=jnp.int32)
    word_of = lanes // 32
    bit_of = jnp.left_shift(jnp.int32(1), lanes % 32)
    flat = seed_new_ids * W + word_of[:, None]
    vals = jnp.broadcast_to(bit_of[:, None], seed_new_ids.shape)
    seed_bits = (
        jnp.zeros((n_tot + 1) * W, jnp.int32)
        .at[flat.ravel()]
        .add(vals.ravel())
        .reshape(n_tot + 1, W)
        .at[n_tot]
        .set(0)
    )
    state = TopoState(node_epoch, jnp.zeros((n_tot + 1, W), dtype=jnp.int32))
    if passes <= 0:
        state = _sweep_adaptive(level_starts, garrays, seed_bits, state)
    else:
        sb = seed_bits
        for _ in range(passes):
            state, _ = _topo_sweep_impl(level_starts, garrays, sb, state, 0)
            sb = jnp.zeros_like(seed_bits)  # only the first pass seeds
    newly_bits = jnp.where(
        is_real[:, None] & ~g_invalid[perm_clipped][:, None],
        state.invalid_bits, 0,
    )
    lane_counts = _lane_counts_blocked(newly_bits, W)
    union = (newly_bits != 0).any(axis=1)
    oob = g_invalid.shape[0]
    newly_dense = (
        jnp.zeros_like(g_invalid)
        .at[jnp.where(union, perm_clipped, oob)]
        .set(True, mode="drop")
    )
    return g_invalid | newly_dense, lane_counts, newly_dense


@functools.lru_cache(maxsize=8)
def topo_mirror_fused_lanes_chain_step(
    level_starts: Tuple[int, ...], n_tot: int, words: int, passes: int,
    depth: int,
):
    """``depth`` consecutive lane bursts in ONE dispatch — the loop-carried-
    dependence composition of the wave chain (PAPERS.md "Julia GraphBLAS
    with Nonblocking Execution"): a ``lax.scan`` carries the dense invalid
    state from stage to stage, so stage ``i`` sees exactly the state stages
    ``< i`` left, with NO host round trip between them. Semantics per stage
    = :func:`topo_mirror_fused_lanes_step` (groups within a stage are
    snapshot-independent; stages apply sequentially) — a fused chain of K
    stages is oracle-identical to K sequential burst dispatches.

    Takes ``seed_mats`` int32[depth, 32*words, S] (NEW-id seed rows, padded
    with ``n_tot``) and returns ``(g_invalid2, lane_counts
    int32[depth, 32*words], packed_stages uint32[depth, ceil(dense/32)])``
    — per-STAGE newly masks, so the host can apply (and fence) each
    logical wave under its own identity while the next chain runs."""
    import jax
    from jax import lax

    W = words

    @jax.jit
    def chain(garrays: TopoGraphArrays, node_epoch0, perm_clipped, g_invalid, seed_mats):
        def stage(g_inv, seed_new_ids):
            g_inv2, lane_counts, newly_dense = _lanes_stage_body(
                level_starts, n_tot, W, passes,
                garrays, node_epoch0, perm_clipped, g_inv, seed_new_ids,
            )
            return g_inv2, (lane_counts, _pack_bool_bits(newly_dense))

        g_invalid2, (lane_counts, packed_stages) = lax.scan(
            stage, g_invalid, seed_mats
        )
        return g_invalid2, lane_counts, packed_stages

    return chain


def topo_mirror_superround_step(
    level_starts, n_tot: int, words: int, passes: int,
    base: int, n_rows: int, fn, update_valid: bool,
):
    """K live rounds of (lane-burst sweep → columnar refresh through the
    memo-table device loader → packed fence-mask extraction) as ONE jitted
    loop-carried ``lax.scan`` — the resident super-round program (ISSUE 14,
    the FuseFlow-style fusion ACROSS pipeline-stage boundaries). The carry
    holds the dense invalid state AND the memo columns (values + validity),
    so round ``i+1`` cascades against exactly the state round ``i`` left —
    burst, refresh, and fence extraction for the whole super-round run with
    zero host round trips between rounds.

    Per-round semantics = :func:`topo_mirror_fused_lanes_step` followed by
    the block's device refresh (``TpuGraphBackend.refresh_block_on_device``)
    — a super-round of K rounds is oracle-identical to K sequential
    (burst → refresh) pairs. The depth comes from ``seed_mats.shape[0]`` at
    trace time, so ONE returned program object serves every pinned depth
    (jit re-traces per shape; the persistent XLA cache keeps each compiled
    executable across restarts). Returns ``(g_invalid2, values2, valid2,
    lane_counts int32[K, 32*words], packed uint32[K, ceil(dense/32)])`` —
    per-ROUND packed fence masks, so the host drain applies (and fences)
    each logical wave under its own identity while the next super-round
    executes.

    ``fn`` is the memo table's device loader ``(ids, *largs) -> rows``;
    its state rides as trailing runtime args, never closure constants."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .bitops import pack_bool_bits

    W = words

    @jax.jit
    def superround(values, valid_dev, garrays, node_epoch0, perm_clipped,
                   g_invalid, seed_mats, *largs):
        def round_step(carry, seed_new_ids):
            g_inv, values, valid_dev = carry
            g_inv2, lane_counts, newly_dense = _lanes_stage_body(
                level_starts, n_tot, W, passes,
                garrays, node_epoch0, perm_clipped, g_inv, seed_new_ids,
            )
            # columnar refresh: the block's invalid rows recompute through
            # the table's device loader and their invalid bits clear, so
            # the NEXT round cascades against a consistent block
            stale = lax.slice_in_dim(g_inv2, base, base + n_rows)
            ids = jnp.arange(n_rows, dtype=jnp.int32)
            fresh = fn(ids, *largs)
            mask = stale.reshape((n_rows,) + (1,) * (values.ndim - 1))
            values2 = jnp.where(mask, fresh, values)
            inv3 = lax.dynamic_update_slice_in_dim(
                g_inv2, jnp.zeros(n_rows, dtype=g_inv2.dtype), base, 0,
            )
            valid2 = (valid_dev | stale) if update_valid else valid_dev
            return (inv3, values2, valid2), (
                lane_counts, pack_bool_bits(newly_dense)
            )

        (inv_f, values_f, valid_f), (lane_counts, packed) = lax.scan(
            round_step, (g_invalid, values, valid_dev), seed_mats
        )
        return inv_f, values_f, valid_f, lane_counts, packed

    return superround


@functools.lru_cache(maxsize=8)
def topo_mirror_gate_lanes_step(n_tot: int, words: int):
    """Lane-packed gate: ``32*words`` INDEPENDENT command groups, group g
    seeding word ``g//32`` bit ``g%32``. Each lane gets dense-BFS semantics
    from the graph's CURRENT invalid state (same gate as the union burst);
    groups are snapshot-independent, exactly like the static bench's packed
    waves. ``seed_new_ids`` is int32[32*words, S] of NEW (topo-order) ids,
    padded with ``n_tot``; ids must be UNIQUE within a lane (seed bits
    accumulate by scatter-add — the caller dedups, which it does anyway to
    define a group). The device-side seed scatter keeps the upload O(total
    seeds), never the O(n·W) bit matrix (16 MB/burst at 1M nodes through
    the relay)."""
    import jax
    import jax.numpy as jnp

    W = words
    L = 32 * W

    @jax.jit
    def gate(is_real, node_epoch0, perm_clipped, g_invalid, seed_new_ids):
        blocked = (
            jnp.where(is_real, g_invalid[perm_clipped], False)
            .astype(jnp.int32)
            .at[n_tot]
            .set(0)
        )
        node_epoch = jnp.where(blocked.astype(bool), -3, node_epoch0)
        lanes = jnp.arange(L, dtype=jnp.int32)
        word_of = lanes // 32
        bit_of = jnp.left_shift(jnp.int32(1), lanes % 32)  # lane 31 wraps negative: same bit pattern
        flat = seed_new_ids * W + word_of[:, None]  # row-major [n_tot+1, W] index
        vals = jnp.broadcast_to(bit_of[:, None], seed_new_ids.shape)
        seed_bits = (
            jnp.zeros((n_tot + 1) * W, jnp.int32)
            .at[flat.ravel()]
            .add(vals.ravel())  # within-lane unique ⇒ add ≡ or (disjoint bits across lanes)
            .reshape(n_tot + 1, W)
            .at[n_tot]
            .set(0)
        )
        # seeds CONDUCT even when already invalid (same rule as the union
        # gate / ops/wave.py run_waves_union); blocked rows still can't
        # receive, and the finish step excludes pre-invalid rows from counts
        return node_epoch, seed_bits

    return gate


@functools.lru_cache(maxsize=8)
def topo_mirror_finish_lanes_step(n_tot: int, words: int):
    """Lane-packed epilogue: per-lane closure popcounts + the newly-union
    as a device-packed DENSE bitmask in one readback, dense-state writeback
    on device (see :func:`topo_mirror_fused_lanes_step` on why packed).
    Returns (g_invalid2, lane_counts int32[32*words], union count,
    packed_newly uint32[ceil(dense/32)])."""
    import jax
    import jax.numpy as jnp

    W = words

    @jax.jit
    def finish(is_real, perm_clipped, g_invalid, final_bits):
        # ~pre-invalid: a conducting already-invalid seed is not NEWLY in
        # any lane (same rule as the union finish)
        newly_bits = jnp.where(
            is_real[:, None] & ~g_invalid[perm_clipped][:, None], final_bits, 0
        )
        lane_counts = _lane_counts_blocked(newly_bits, W)  # one-pass popcounts
        union = (newly_bits != 0).any(axis=1)
        union_count = union.sum(dtype=jnp.int32)
        oob = g_invalid.shape[0]
        newly_dense = (
            jnp.zeros_like(g_invalid)
            .at[jnp.where(union, perm_clipped, oob)]
            .set(True, mode="drop")
        )
        g_invalid2 = g_invalid | newly_dense
        return g_invalid2, lane_counts, union_count, _pack_bool_bits(newly_dense)

    return finish


@functools.lru_cache(maxsize=8)
def topo_sweep_step(level_starts: Tuple[int, ...], start_level: int = 1):
    """Jitted sweep for one level layout: ``step(garrays, seed_bits, state)``.

    Level boundaries are compile-time (they shape the program); the graph
    arrays stay runtime args so content updates never recompile.
    ``start_level=0`` includes level 0 — needed only by multi-pass sweeps
    over patched mirrors (an edge into a level-0 row)."""
    import jax

    return jax.jit(
        functools.partial(_topo_sweep_impl, level_starts, start_level=start_level)
    )


def build_topo_wave32(graph: TopoGraph, words: int = 1):
    """(state0, wave32) — same contract as build_pull_wave32, but the whole
    ``32*words``-wave cascade costs one table pass. ``wave32(seed_bits,
    state)`` → (state, newly-invalidated count over real nodes)."""
    garrays = topo_graph_arrays(graph)
    step = topo_sweep_step(graph.level_starts)

    def wave32(seed_bits, state):
        return step(garrays, seed_bits, state)

    wave32.garrays = garrays
    wave32.step = step
    wave32.impl = functools.partial(_topo_sweep_impl, graph.level_starts)
    return topo_init_state(graph.n_tot, words), wave32
