"""Device kernels: the invalidation-wave BFS (jit) + pallas variants +
the vectorized memoization table."""
from .memo_bridge import MemoTableBridge
from .memo_table import MemoTable
from .wave import GraphArrays, run_wave, run_wave_with_stats, seeds_to_frontier, wave_step

__all__ = [
    "GraphArrays",
    "MemoTable",
    "MemoTableBridge",
    "run_wave",
    "run_wave_with_stats",
    "seeds_to_frontier",
    "wave_step",
]
