"""stl_fusion_tpu — a TPU-native reactive-memoization framework.

A ground-up rebuild of the capabilities of Stl.Fusion (reference:
/root/reference, C#/.NET) designed TPU-first:

- transparent memoization of async functions into versioned ``Computed``
  nodes with automatic runtime dependency capture (``@compute_method``);
- **cascading invalidation** through the dependency DAG — executed on the
  hot path as batched sparse-BFS frontier expansion over a CSR mirror of
  the graph in TPU HBM (``stl_fusion_tpu.ops`` / ``graph``), not as the
  reference's lock-per-node recursive host walk;
- reactive state containers (``MutableState`` / ``ComputedState``);
- a command pipeline whose completions replay as invalidations
  (``commands`` + ``operations``);
- invalidation-aware RPC with per-call invalidation subscriptions
  (``rpc`` + ``client``), multi-host invalidation via a durable operation
  log (``oplog``), and intra-pod frontier exchange over XLA collectives
  (``parallel``);
- chaos-hardened failure handling (``resilience``): deterministic fault
  injection, per-peer circuit breakers, and a device-wave watchdog with a
  split-host-loop fallback — see RESILIENCE.md;
- an elastic cluster control plane (``cluster``): heartbeat membership,
  an epoch-versioned rendezvous shard map, epoch-stamped routing with
  read failover, and live resharding that fences moved keys' client
  caches — see CLUSTER.md.

See SURVEY.md for the reference structural map this build follows.
"""

__version__ = "0.1.0"

from .utils import (  # noqa: F401
    AsyncEvent,
    LTag,
    Result,
    TestClock,
    TransientError,
)
from .checkpoint import (  # noqa: F401
    CheckpointManager,
    HubCheckpoint,
    load_graph,
    save_graph,
)
